"""Multi-tenant batched serving (docs/SERVING.md; ROADMAP item 1).

Covers the space×batch mesh layer (parallel.mesh.BatchedGrid +
exchange_halo_batched + the batched deep sweep), the per-lane bitwise
parity contract of every model's batched_advance_fn, the bin
scheduler's key/packing determinism, the service driver end to end
(program count == len(bins), compiles.steady_state == 0, session
checkpoint multiplexing, preemption requeue, queue-driven elasticity),
the batched traffic audit + its doctored over-padded fixture, the
serve-request/bin-manifest schema gate, and the monitor's SERVE badge.

The acceptance drill: a heterogeneous 50-request trace through
apps/serve.py compiles exactly len(bins) programs with
`compiles.steady_state == 0`, every request's result bitwise-equal to
its standalone single-run twin. The gloo-real 2-rank edition drives
tests/serving_worker.py via spawn_ranks.
"""

from __future__ import annotations

import json
import os
import pathlib
import signal
import subprocess
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

REPO = pathlib.Path(__file__).resolve().parent.parent

from rocm_mpi_tpu.config import DiffusionConfig  # noqa: E402
from rocm_mpi_tpu.models import HeatDiffusion  # noqa: E402
from rocm_mpi_tpu.models.swe import SWEConfig, ShallowWater  # noqa: E402
from rocm_mpi_tpu.models.wave import AcousticWave, WaveConfig  # noqa: E402
from rocm_mpi_tpu.parallel import mesh as pmesh  # noqa: E402
from rocm_mpi_tpu.serving import bins as sbins  # noqa: E402
from rocm_mpi_tpu.serving.queue import (  # noqa: E402
    DEFAULT_RETRY_AFTER_S,
    MAX_RETRY_AFTER_S,
    RETRY_WINDOW_STALE_S,
    Request,
    RequestQueue,
    load_trace,
    request_from_record,
    request_to_record,
    validate_request_record,
)
from rocm_mpi_tpu.serving.service import (  # noqa: E402
    ServeConfig,
    SimulationService,
)
from rocm_mpi_tpu.telemetry import compiles  # noqa: E402


def _put(arr, sharding):
    return jax.device_put(np.asarray(arr), sharding)


# ---------------------------------------------------------------------------
# The space×batch mesh layer
# ---------------------------------------------------------------------------


def test_batched_grid_shapes_and_specs():
    bg = pmesh.init_batched_grid(
        6, 16, 16, space_dims=(1, 2), batch_dims=2,
        devices=jax.devices()[:4],
    )
    assert bg.axis_names == ("batch", "gx", "gy")
    assert bg.batch == 6 and bg.batch_dims == 2 and bg.local_batch == 3
    assert bg.global_shape == (6, 16, 16)
    assert bg.local_shape == (3, 16, 8)
    assert tuple(bg.spec) == ("batch", "gx", "gy")
    assert tuple(bg.aux_spec) == ("gx", "gy")
    assert bg.space.dims == (1, 2)


def test_batched_grid_validation():
    with pytest.raises(ValueError, match="not divisible"):
        pmesh.init_batched_grid(3, 16, 16, space_dims=(1, 1),
                                batch_dims=2, devices=jax.devices()[:2])
    with pytest.raises(ValueError, match="devices"):
        pmesh.init_batched_grid(4, 16, 16, space_dims=(2, 2),
                                batch_dims=4, devices=jax.devices())


def test_rebuild_batched_for_mesh_grows_rows():
    bg = pmesh.init_batched_grid(4, 16, 16, space_dims=(1, 1),
                                 batch_dims=1, devices=jax.devices()[:1])
    grown = pmesh.rebuild_batched_for_mesh(
        bg, batch_dims=2, devices=jax.devices()[:2]
    )
    assert grown.batch_dims == 2 and grown.batch == 4
    assert grown.space.global_shape == bg.space.global_shape


def test_exchange_halo_batched_rejects_stateful_wire():
    from rocm_mpi_tpu.parallel.halo import exchange_halo_batched

    bg = pmesh.init_batched_grid(2, 16, 16, space_dims=(1, 1),
                                 batch_dims=1, devices=jax.devices()[:1])
    with pytest.raises(ValueError, match="stateful"):
        exchange_halo_batched(jnp.zeros((2, 16, 16)), bg,
                              wire_mode="int8")


# ---------------------------------------------------------------------------
# Per-lane bitwise parity: batched advance == N standalone runs
# ---------------------------------------------------------------------------


LANE_STEPS = [5, 3, 5, 1]


def test_diffusion_batched_parity_heterogeneous_steps():
    """The serving contract: every lane of a (space-sharded, lane-
    sharded) batched advance is bitwise-equal to a standalone run of
    its own length — the per-lane freeze select is exact."""
    B, n = 4, max(LANE_STEPS)
    cfg = DiffusionConfig(global_shape=(16, 16), nt=8, warmup=0,
                          dtype="f64", dims=(1, 2))
    m = HeatDiffusion(cfg, devices=jax.devices()[:2])
    adv_b, bg = m.batched_advance_fn(batch=B, batch_dims=2)
    T0, Cp = m.init_state()
    lanes = np.stack(
        [np.asarray(T0) * (1 + 0.1 * i) for i in range(B)]
    )
    out = np.asarray(adv_b(
        _put(lanes, bg.sharding),
        _put(Cp, bg.aux_sharding),
        _put(np.array(LANE_STEPS, np.int32), bg.batch_sharding),
        n,
    ))
    adv1 = m.advance_fn("shard")
    for i in range(B):
        ref = np.asarray(adv1(
            _put(lanes[i], m.grid.sharding), Cp, LANE_STEPS[i]
        ))
        assert np.array_equal(out[i], ref), f"lane {i}"


def test_wave_batched_parity():
    B, n = 4, max(LANE_STEPS)
    cfg = WaveConfig(global_shape=(16, 16), nt=8, warmup=0,
                     dtype="f64", dims=(1, 2))
    w = AcousticWave(cfg, devices=jax.devices()[:2])
    adv_b, bg = w.batched_advance_fn(batch=B, batch_dims=2)
    U0, _, C2 = w.init_state()
    ul = np.stack([np.asarray(U0) * (1 + 0.1 * i) for i in range(B)])
    oU, oUp = adv_b(
        _put(ul, bg.sharding), _put(ul.copy(), bg.sharding),
        _put(C2, bg.aux_sharding),
        _put(np.array(LANE_STEPS, np.int32), bg.batch_sharding), n,
    )
    oU, oUp = np.asarray(oU), np.asarray(oUp)
    adv1 = w.advance_fn("shard")
    for i in range(B):
        rU, rUp = adv1(
            _put(ul[i], w.grid.sharding),
            _put(ul[i].copy(), w.grid.sharding), C2, LANE_STEPS[i],
        )
        assert np.array_equal(oU[i], np.asarray(rU)), f"lane {i} U"
        assert np.array_equal(oUp[i], np.asarray(rUp)), f"lane {i} U⁻"


def test_swe_batched_parity():
    B, n = 4, max(LANE_STEPS)
    cfg = SWEConfig(global_shape=(16, 16), nt=8, warmup=0,
                    dtype="f64", dims=(1, 2))
    s = ShallowWater(cfg, devices=jax.devices()[:2])
    adv_b, bg = s.batched_advance_fn(batch=B, batch_dims=2)
    h0, _ = s.init_state()
    Mus = s.face_masks()
    hl = np.stack([np.asarray(h0) * (1 + 0.1 * i) for i in range(B)])
    zeros_b = np.zeros((B,) + cfg.global_shape)
    oh, ous = adv_b(
        _put(hl, bg.sharding),
        tuple(_put(zeros_b, bg.sharding) for _ in range(2)),
        tuple(_put(M, bg.aux_sharding) for M in Mus),
        _put(np.array(LANE_STEPS, np.int32), bg.batch_sharding), n,
    )
    oh = np.asarray(oh)
    adv1 = s.advance_fn("shard")
    for i in range(B):
        rh, rus = adv1(
            _put(hl[i], s.grid.sharding),
            tuple(_put(np.zeros(cfg.global_shape), s.grid.sharding)
                  for _ in range(2)),
            Mus, LANE_STEPS[i],
        )
        assert np.array_equal(oh[i], np.asarray(rh)), f"lane {i} h"
        for a in range(2):
            assert np.array_equal(
                np.asarray(ous[a])[i], np.asarray(rus[a])
            ), f"lane {i} u{a}"


def test_diffusion_batched_deep_parity():
    """The batched deep sweep (make_deep_sweep on a BatchedGrid, jnp
    local form) matches the standalone jnp deep schedule per lane."""
    import functools

    from jax import lax

    from rocm_mpi_tpu.parallel.deep_halo import make_deep_sweep

    B = 4
    cfg = DiffusionConfig(global_shape=(16, 16), nt=8, warmup=0,
                          dtype="f64", dims=(1, 2))
    m = HeatDiffusion(cfg, devices=jax.devices()[:2])
    adv_b, bg, k = m.batched_deep_advance_fn(batch=B, batch_dims=2,
                                             block_steps=4)
    assert k == 4
    T0, Cp = m.init_state()
    lanes = np.stack(
        [np.asarray(T0) * (1 + 0.1 * i) for i in range(B)]
    )
    out = np.asarray(adv_b(
        _put(lanes, bg.sharding), _put(Cp, bg.aux_sharding), 8
    ))

    sched = make_deep_sweep(m.grid, 4, cfg.lam, cfg.jax_dtype(cfg.dt),
                            cfg.spacing, local_form="jnp")

    @functools.partial(jax.jit, donate_argnums=0)
    def adv1(T, Cp_, ns):
        Cm = sched.prepare(Cp_)
        return lax.fori_loop(
            0, ns // 4, lambda _, x: sched.sweep(x, Cm), T
        )

    for i in range(B):
        ref = np.asarray(adv1(_put(lanes[i], m.grid.sharding), Cp, 8))
        assert np.array_equal(out[i], ref), f"deep lane {i}"


def test_batched_deep_rejects_stateful_wire():
    from rocm_mpi_tpu.parallel.deep_halo import make_deep_sweep

    bg = pmesh.init_batched_grid(2, 16, 16, space_dims=(1, 1),
                                 batch_dims=1, devices=jax.devices()[:1])
    with pytest.raises(ValueError, match="stateful"):
        make_deep_sweep(bg, 4, 1.0, 0.1, (0.5, 0.5), wire_mode="int8")


def test_batched_advance_rejects_pallas_variants():
    cfg = DiffusionConfig(global_shape=(16, 16), nt=8, warmup=0,
                          dtype="f64", dims=(1, 1))
    m = HeatDiffusion(cfg, devices=jax.devices()[:1])
    with pytest.raises(ValueError, match="single-lane"):
        m.batched_advance_fn(batch=2, variant="perf")


# ---------------------------------------------------------------------------
# Bin keys, buckets, packing
# ---------------------------------------------------------------------------


def test_bin_key_round_trip():
    req = Request(request_id="r1", workload="swe",
                  global_shape=(24, 48), dtype="f32", nt=37,
                  physics=(("g", 9.81), ("H0", 2.0)),
                  wire_mode="bf16")
    key = sbins.bin_key(req)
    assert key.steps_bucket == 64
    assert key.physics == (("H0", 2.0), ("g", 9.81))  # sorted
    parsed = sbins.BinKey.parse(key.key_str())
    assert parsed == key


def test_bin_key_physics_order_cannot_split_a_bin():
    a = Request(request_id="a", physics=(("lam", 2.0), ("cp0", 3.0)))
    b = Request(request_id="b", physics=(("cp0", 3.0), ("lam", 2.0)))
    assert sbins.bin_key(a) == sbins.bin_key(b)


def test_steps_bucket():
    assert [sbins.steps_bucket(n) for n in (1, 2, 3, 8, 9, 64, 65)] == \
        [1, 2, 4, 8, 16, 64, 128]
    with pytest.raises(ValueError):
        sbins.steps_bucket(0)


@pytest.mark.parametrize("n,max_w,floor,want", [
    (1, 8, 0.5, [1]),
    (2, 8, 0.5, [2]),
    (3, 8, 0.5, [4]),
    (5, 8, 0.5, [8]),
    (9, 8, 0.5, [8, 1]),
    (5, 8, 0.8, [4, 1]),  # the split rule: 5/8 < 0.8 -> narrower class
    (13, 4, 0.5, [4, 4, 4, 1]),
])
def test_plan_batches(n, max_w, floor, want):
    assert sbins.plan_batches(n, max_w, floor) == want
    # determinism: same inputs, same plan
    assert sbins.plan_batches(n, max_w, floor) == want


def test_bin_stats_waste_accounting():
    st = sbins.BinStats(key=sbins.bin_key(Request(request_id="x")))
    st.note_batch(4, [6, 3, 6], 6)  # one idle lane + one short lane
    assert st.occupancy == 0.75
    assert st.padding_waste == pytest.approx(1 - 15 / 24)
    st.note_batch(1, [6], 6, split=True)
    assert st.splits == 1


# ---------------------------------------------------------------------------
# Request schema + queue
# ---------------------------------------------------------------------------


def test_request_record_round_trip(tmp_path):
    req = Request(request_id="rt-1", workload="wave",
                  global_shape=(16, 16), dtype="f64", nt=9,
                  physics=(("c0", 2.0),), ic_scale=1.25,
                  session="s1")
    rec = request_to_record(req)
    assert validate_request_record(rec) == []
    assert request_from_record(rec) == req
    path = tmp_path / "trace.jsonl"
    path.write_text(json.dumps(rec) + "\n\n" + json.dumps(rec) + "\n")
    assert load_trace(path) == [req, req]


def test_request_validation():
    with pytest.raises(ValueError, match="workload"):
        Request(request_id="x", workload="plasma")
    with pytest.raises(ValueError, match="nt"):
        Request(request_id="x", nt=0)
    with pytest.raises(ValueError, match="session"):
        Request(request_id="x", resume=True)
    bad = request_to_record(Request(request_id="ok"))
    bad["nt"] = -2
    assert any("nt" in p for p in validate_request_record(bad))


def test_queue_fifo_requeue_front():
    q = RequestQueue()
    t1 = q.submit(Request(request_id="a"))
    t2 = q.submit(Request(request_id="b"))
    got = q.pop_pending()
    assert [t.request.request_id for t in got] == ["a", "b"]
    q.requeue([t1])
    t3 = q.submit(Request(request_id="c"))
    got2 = q.pop_pending()
    assert [t.request.request_id for t in got2] == ["a", "c"]
    assert q.counters()["requeued"] == 1
    del t2, t3


# ---------------------------------------------------------------------------
# The service driver
# ---------------------------------------------------------------------------


def _mixed_trace(tag: str, scale0: float = 1.0):
    mix = [
        ("diffusion", (16, 16), 5), ("diffusion", (16, 16), 7),
        ("diffusion", (24, 24), 6), ("wave", (16, 16), 5),
        ("swe", (16, 16), 4), ("diffusion", (16, 16), 3),
    ]
    return [
        Request(request_id=f"{tag}-{i}", workload=wl, global_shape=sh,
                dtype="f64", nt=nt, ic_scale=scale0 + 0.05 * i)
        for i, (wl, sh, nt) in enumerate(mix)
    ]


def test_service_trace_bins_programs_steady_and_parity():
    """The acceptance shape, in-process: a heterogeneous trace (3 shape
    classes, mixed physics/steps) compiles exactly len(bins) programs,
    compiles.steady_state == 0, a repeat trace compiles NOTHING, and
    every result is bitwise-equal to its standalone twin."""
    compiles.install()
    svc = SimulationService(config=ServeConfig(max_width=4))
    trace = _mixed_trace("e2e")
    tickets = [svc.queue.submit(r) for r in trace]
    report = svc._drain_all()
    assert report.served == len(trace) and report.failed == 0
    assert report.n_programs == len(report.programs)
    assert report.n_programs == report.n_bins + sum(
        max(len(st.widths) - 1, 0) for st in report.bins.values()
    )
    assert report.compiles["steady_state"] == 0

    # steady state: the identical mix again compiles zero new programs
    before = compiles.snapshot()["totals"]["backend_compiles"]
    report2 = svc.run_trace(_mixed_trace("e2e2"))
    after = compiles.snapshot()["totals"]["backend_compiles"]
    assert after == before, "steady-state recompile"
    assert report2.compiles["steady_state"] == 0

    # bitwise parity vs standalone twins (lane 0 and lane 1 share a bin)
    r0 = tickets[0].result(timeout=5)
    cfg = DiffusionConfig(global_shape=(16, 16), nt=8, warmup=0,
                          dtype="f64", dims=(1, 1))
    m = HeatDiffusion(cfg, devices=jax.devices()[:1])
    T0, Cp = m.init_state()
    adv = m.advance_fn("shard")
    ref = np.asarray(adv(
        jnp.asarray(np.asarray(T0) * trace[0].ic_scale), Cp,
        trace[0].nt,
    ))
    assert np.array_equal(r0[0], ref)

    wv = tickets[3].result(timeout=5)
    wcfg = WaveConfig(global_shape=(16, 16), nt=8, warmup=0,
                      dtype="f64", dims=(1, 1))
    w = AcousticWave(wcfg, devices=jax.devices()[:1])
    U0, _, C2 = w.init_state()
    U0s = np.asarray(U0) * trace[3].ic_scale
    wadv = w.advance_fn("shard")
    rU, rUp = wadv(jnp.asarray(U0s), jnp.asarray(U0s.copy()), C2,
                   trace[3].nt)
    assert np.array_equal(wv[0], np.asarray(rU))
    assert np.array_equal(wv[1], np.asarray(rUp))


def test_service_manifest_schema_and_cli_gate(tmp_path):
    svc = SimulationService(config=ServeConfig(max_width=4))
    svc.run_trace(_mixed_trace("man"))
    path = tmp_path / "serve-manifest.json"
    doc = svc.write_manifest(path)
    assert sbins.validate_manifest_doc(doc) == []
    trace_path = tmp_path / "serve-requests.jsonl"
    with open(trace_path, "w") as fh:
        for r in _mixed_trace("man"):
            fh.write(json.dumps(request_to_record(r)) + "\n")

    from rocm_mpi_tpu.telemetry.regress import check_schema

    assert check_schema([path, trace_path]) == []
    # doctored manifest: occupancy outside [0,1] must fail the gate
    doc["bins"][0]["occupancy"] = 1.7
    bad = tmp_path / "bad-manifest.json"
    bad.write_text(json.dumps(doc))
    assert any("occupancy" in p for p in check_schema([bad]))


def test_service_unknown_physics_fails_request_loudly():
    svc = SimulationService(config=ServeConfig(max_width=2))
    t = svc.queue.submit(Request(
        request_id="bad-phys", workload="diffusion",
        global_shape=(16, 16), dtype="f64", nt=2,
        physics=(("viscosity", 1.0),),
    ))
    report = svc._drain_all()
    assert report.failed == 1 and report.served == 0
    with pytest.raises(RuntimeError, match="physics"):
        t.result(timeout=5)


def test_service_session_checkpoint_multiplex_and_resume(tmp_path):
    """Per-session checkpoints ride the PR-6 manifest machinery: a
    served session banks a step-nt checkpoint whose manifest meta
    carries the request id; a resume request continues from it and the
    two-leg result is bitwise-equal to one uninterrupted run."""
    from rocm_mpi_tpu.utils import checkpoint as ckpt

    sessions = tmp_path / "sessions"
    svc = SimulationService(config=ServeConfig(
        max_width=2, sessions_dir=str(sessions),
    ))
    leg1 = Request(request_id="leg1", workload="diffusion",
                   global_shape=(16, 16), dtype="f64", nt=4,
                   ic_scale=1.1, session="sess-a")
    t1 = svc.queue.submit(leg1)
    svc._drain_all()
    assert t1.result(timeout=5) is not None
    sdir = sessions / "sess-a"
    assert ckpt.latest_valid_step(sdir) == 4
    manifest = ckpt.read_manifest(sdir, 4)
    assert manifest["meta"]["extra"]["serving"]["request_id"] == "leg1"

    # leg 2: resume to nt=9 (5 more steps)
    leg2 = Request(request_id="leg2", workload="diffusion",
                   global_shape=(16, 16), dtype="f64", nt=9,
                   ic_scale=1.1, session="sess-a", resume=True)
    t2 = svc.queue.submit(leg2)
    svc._drain_all()
    out = t2.result(timeout=5)
    assert t2.start_step == 4 and t2.steps_run == 5

    cfg = DiffusionConfig(global_shape=(16, 16), nt=16, warmup=0,
                          dtype="f64", dims=(1, 1))
    m = HeatDiffusion(cfg, devices=jax.devices()[:1])
    T0, Cp = m.init_state()
    adv = m.advance_fn("shard")
    ref = np.asarray(adv(jnp.asarray(np.asarray(T0) * 1.1), Cp, 9))
    assert np.array_equal(out[0], ref)


def test_resume_past_nt_fails_that_lane_only(tmp_path):
    """A session already past the requested nt has no checkpoint to
    hand back: the lane fails loudly — and ONLY that lane; a valid
    co-batched neighbor still gets served (tenant isolation)."""
    sessions = tmp_path / "sessions"
    svc = SimulationService(config=ServeConfig(
        max_width=2, sessions_dir=str(sessions),
    ))
    svc.run_trace([Request(
        request_id="seed", workload="diffusion", global_shape=(16, 16),
        dtype="f64", nt=4, session="sess-b",
    )])
    bad = svc.queue.submit(Request(
        request_id="past", workload="diffusion", global_shape=(16, 16),
        dtype="f64", nt=2, session="sess-b", resume=True,
    ))
    good = svc.queue.submit(Request(
        request_id="fresh", workload="diffusion", global_shape=(16, 16),
        dtype="f64", nt=2,
    ))
    report = svc._drain_all()
    assert report.failed == 1
    with pytest.raises(RuntimeError, match="already at step"):
        bad.result(timeout=5)
    assert good.result(timeout=5) is not None


def test_requeued_ticket_result_returns_none_promptly():
    q = RequestQueue()
    t = q.submit(Request(request_id="r"))
    q.pop_pending()
    q.requeue([t])
    # No timeout burn: the requeue wakes waiters immediately.
    assert t.result(timeout=5) is None
    assert t.state == "requeued"
    # Re-popped by the next drain: the wait re-arms for the real result.
    q.pop_pending()
    assert t.state == "running" and not t.done()


def test_transient_batch_error_retries_then_serves(monkeypatch):
    """A non-ValueError batch failure (e.g. checkpoint corruption is a
    RuntimeError) is TRANSIENT: the tickets ride the retry budget and
    the retried batch serves them — never strands popped tickets in
    'running', never kills the drain, and never dies on first fault."""
    from rocm_mpi_tpu.resilience.policy import RequestRetryPolicy

    svc = SimulationService(config=ServeConfig(
        max_width=1, retry=RequestRetryPolicy(budget=2,
                                              backoff_base_s=0.0),
        # the drill monkeypatches the SERIAL chokepoint; the pipelined
        # editions of this failure class live in
        # test_pipelined_prepare_failure_retries / _resolve_failure
        pipeline_depth=1,
    ))
    orig = svc._execute_batch
    calls = {"n": 0}

    def flaky(key, tickets, width, split):
        calls["n"] += 1
        if calls["n"] == 1:
            raise RuntimeError("storage bit rot")
        return orig(key, tickets, width, split)

    monkeypatch.setattr(svc, "_execute_batch", flaky)
    t1 = svc.queue.submit(Request(
        request_id="x1", workload="diffusion", global_shape=(16, 16),
        dtype="f64", nt=2,
    ))
    t2 = svc.queue.submit(Request(
        request_id="x2", workload="diffusion", global_shape=(16, 16),
        dtype="f64", nt=3,
    ))
    report = svc._drain_all()
    assert report.failed == 0 and report.served == 2
    assert t1.retries == 1 and t1.state == "done"
    assert t1.result(timeout=5) is not None
    assert t2.result(timeout=5) is not None


def test_retry_budget_exhausted_quarantines(tmp_path, monkeypatch):
    """A request that fails EVERY batch it joins must not be re-batched
    forever: after the retry budget it is terminally quarantined, its
    full record banked to the append-only ledger for offline repro —
    and the accounting invariant still balances."""
    from rocm_mpi_tpu.resilience.policy import (
        CircuitPolicy,
        RequestRetryPolicy,
    )
    from rocm_mpi_tpu.serving.queue import (
        load_quarantine,
        validate_quarantine_record,
    )

    qpath = tmp_path / "quarantine.jsonl"
    svc = SimulationService(config=ServeConfig(
        max_width=1,
        retry=RequestRetryPolicy(budget=2, backoff_base_s=0.0),
        # the breaker would otherwise open mid-drill and reject the
        # retries before the budget empties
        circuit=CircuitPolicy(k=0),
        quarantine_path=str(qpath),
        pipeline_depth=1,  # the drill monkeypatches the serial seam
    ))

    def always_broken(key, tickets, width, split):
        raise RuntimeError("poison program class")

    monkeypatch.setattr(svc, "_execute_batch", always_broken)
    t = svc.queue.submit(Request(
        request_id="poison-1", workload="diffusion",
        global_shape=(16, 16), dtype="f64", nt=2, ic_scale=1.5,
    ))
    report = svc._drain_all()
    assert report.quarantined == 1 and report.failed == 0
    assert t.state == "quarantined" and t.retries == 2
    with pytest.raises(RuntimeError, match="quarantined"):
        t.result(timeout=5)
    assert svc.queue.check_accounting() == []

    records = load_quarantine(qpath)
    assert len(records) == 1
    rec = records[0]
    assert validate_quarantine_record(rec) == []
    assert rec["request_id"] == "poison-1"
    assert rec["retries"] == 2
    # the FULL request record rides along for offline repro
    from rocm_mpi_tpu.serving.queue import request_from_record

    replay = request_from_record(rec["request"])
    assert replay.ic_scale == 1.5 and replay.nt == 2

    from rocm_mpi_tpu.telemetry.regress import check_schema

    assert check_schema([qpath]) == []
    # a doctored record (no error, negative retries) fails the gate
    import copy

    bad = copy.deepcopy(rec)
    bad["retries"] = -1
    del bad["error"]
    bad_path = tmp_path / "bad-quarantine.jsonl"
    bad_path.write_text(json.dumps(bad) + "\n")
    assert check_schema([bad_path]) != []


def test_deadline_expires_pending_at_pop():
    """A pending ticket past its deadline fails with deadline-exceeded
    AT POP TIME — it never occupies a lane; a ticket with headroom
    serves normally (docs/SERVING.md "SLOs and admission")."""
    q = RequestQueue()
    stale = q.submit(Request(request_id="stale", deadline_s=1e-6))
    fresh = q.submit(Request(request_id="fresh", deadline_s=3600.0))
    popped = q.pop_pending()
    assert [t.request.request_id for t in popped] == ["fresh"]
    assert stale.state == "expired"
    with pytest.raises(RuntimeError, match="deadline-exceeded"):
        stale.result(timeout=5)
    c = q.counters()
    assert c["expired"] == 1
    assert [t.request.request_id for t in q.take_expired()] == ["stale"]
    assert q.check_accounting(in_flight=1) == []


def test_deadline_validation():
    with pytest.raises(ValueError, match="deadline_s"):
        Request(request_id="x", deadline_s=-1.0)
    rec = request_to_record(Request(request_id="ok", deadline_s=2.5))
    assert rec["deadline_s"] == 2.5
    assert request_from_record(rec).deadline_s == 2.5
    rec["deadline_s"] = 0
    assert any("deadline_s" in p for p in validate_request_record(rec))


def test_queue_full_rejects_fast_with_retry_after():
    """Admission control: an over-depth submit returns a terminally
    rejected ticket carrying a retry-after hint — fast, never silently
    dropped — and the books still balance."""
    q = RequestQueue(max_depth=2)
    a = q.submit(Request(request_id="a"))
    b = q.submit(Request(request_id="b"))
    c = q.submit(Request(request_id="c"))
    assert c.state == "rejected" and c.done()
    assert "queue-full" in c.error and "retry-after" in c.error
    with pytest.raises(RuntimeError, match="queue-full"):
        c.result(timeout=5)
    assert q.depth() == 2
    counters = q.counters()
    assert counters["rejected"] == 1 and counters["submitted"] == 3
    assert q.check_accounting() == []
    assert q.retry_after_hint() > 0
    del a, b


def test_retry_after_hint_bounded_on_cold_start():
    """Satellite: with ZERO (or one) completion marks the throughput
    window is empty — a cold-start flood used to derive a degenerate
    0/∞ hint from it. The hint must fall back to the bounded default,
    never 0, never unbounded."""
    q = RequestQueue()
    for i in range(4):
        q.submit(Request(request_id=f"cold{i}"))
    assert q.retry_after_hint() == DEFAULT_RETRY_AFTER_S
    # one mark is still not a window (span needs two endpoints)
    q.note_completed(1)
    assert q.retry_after_hint() == DEFAULT_RETRY_AFTER_S
    # and the queue-full fast-reject path carries the same bounded hint
    q2 = RequestQueue(max_depth=1)
    q2.submit(Request(request_id="a"))
    rej = q2.submit(Request(request_id="b"))
    assert f"retry-after ~{DEFAULT_RETRY_AFTER_S:.2f}s" in rej.error


def test_retry_after_hint_edges_are_clamped():
    """Satellite: every derived-hint edge is pinned into
    [0.01, MAX_RETRY_AFTER_S] — a slow window clamps at the cap, a
    stale window (post-flood idle) and a same-instant burst (span 0)
    fall back to the default, and a fast window never rounds to 0."""
    now = time.monotonic()
    q = RequestQueue()
    for i in range(4):
        q.submit(Request(request_id=f"e{i}"))
    # slow window: 2 completions over 40 s, depth 4 -> 80 s derived,
    # clamped to the cap (an honest "come back much later", bounded)
    q._done_marks[:] = [(now - 50.0, 1), (now - 10.0, 1)]
    assert q.retry_after_hint() == MAX_RETRY_AFTER_S
    # stale window: the newest mark is past RETRY_WINDOW_STALE_S —
    # extrapolating a dead window would be near-infinite; default wins
    q._done_marks[:] = [
        (now - RETRY_WINDOW_STALE_S - 40.0, 8),
        (now - RETRY_WINDOW_STALE_S - 1.0, 8),
    ]
    assert q.retry_after_hint() == DEFAULT_RETRY_AFTER_S
    # span 0: a same-instant completion burst has no rate; default wins
    # (the old derivation divided by it)
    q._done_marks[:] = [(now - 1.0, 3), (now - 1.0, 5)]
    assert q.retry_after_hint() == DEFAULT_RETRY_AFTER_S
    # fast window: huge throughput must floor at 0.01, never 0 — a 0
    # hint invites an instant re-submit hammer
    fast = RequestQueue()
    fast.submit(Request(request_id="f0"))
    fast._done_marks[:] = [(now - 2.0, 1000), (now - 1.0, 1000)]
    assert fast.retry_after_hint() == 0.01


def test_expire_overdue_uses_the_caller_clock():
    """The fleet router's single-writer wall-clock hook: a replica
    queue runs wall_slo=False (no local clock makes SLO decisions),
    and expire_overdue(now=...) expires with the ROUTER's clock — no
    sleeping, the caller just says what time it is."""
    q = RequestQueue()
    q.wall_slo = False
    slow = q.submit(Request(request_id="slow", deadline_s=5.0))
    fresh = q.submit(Request(request_id="fresh", deadline_s=3600.0))
    expired = q.expire_overdue(now=slow.submitted_mono + 10.0)
    assert [t.request.request_id for t in expired] == ["slow"]
    assert slow.state == "expired" and "router clock" in slow.error
    with pytest.raises(RuntimeError, match="deadline-exceeded"):
        slow.result(timeout=5)
    # wall_slo off: pop skips the local deadline check entirely — the
    # fresh ticket serves, and nothing else expired behind our back
    assert [t.request.request_id for t in q.pop_pending()] == ["fresh"]
    c = q.counters()
    assert c["expired"] == 1
    assert [t.request.request_id for t in q.take_expired()] == ["slow"]
    assert q.check_accounting(in_flight=1) == []
    del fresh


def test_requeue_preserves_original_relative_order():
    """Satellite: requeue-at-front is ORDER-PINNED by submission
    ordinal — a 3-ticket preemption requeue (and any sequence of
    single-ticket requeues) replays in original relative order, ahead
    of new arrivals."""
    q = RequestQueue()
    t1 = q.submit(Request(request_id="r1"))
    t2 = q.submit(Request(request_id="r2"))
    t3 = q.submit(Request(request_id="r3"))
    popped = q.pop_pending()
    assert [t.request.request_id for t in popped] == ["r1", "r2", "r3"]
    # the 3-ticket preemption requeue: one call, original order kept
    q.requeue([t1, t2, t3])
    q.submit(Request(request_id="r4"))
    assert [t.request.request_id for t in q.pop_pending()] == \
        ["r1", "r2", "r3", "r4"]
    # the ADVERSARIAL shape: per-batch retry requeues land one at a
    # time, out of submission order — the pop must still replay them
    # in original relative order (the old front-prepend had no pin).
    q.requeue([t3])
    q.requeue([t1])
    q.requeue([t2])
    assert [t.request.request_id for t in q.pop_pending()] == \
        ["r1", "r2", "r3"]


def test_retry_park_timeout_raises_not_none():
    """A RETRY-parked ticket is still owned by the live service: a
    result() timeout during its backoff window raises TimeoutError —
    the preemption None (an invitation to re-submit) would cause
    duplicate execution of a request that is about to be retried."""
    q = RequestQueue()
    t = q.submit(Request(request_id="rp"))
    q.pop_pending()
    q.requeue([t], wake=False)
    with pytest.raises(TimeoutError):
        t.result(timeout=0.05)
    # the preemption park keeps its prompt-None contract
    q.pop_pending()
    q.requeue([t], wake=True)
    assert t.result(timeout=5) is None


def test_retry_backoff_parks_until_eligible():
    """A backoff-parked ticket stays in place at pop time (FIFO
    position preserved) and becomes eligible once not_before passes."""
    q = RequestQueue()
    t1 = q.submit(Request(request_id="b1"))
    t2 = q.submit(Request(request_id="b2"))
    q.pop_pending()
    t1.not_before = time.monotonic() + 30.0
    q.requeue([t1, t2], wake=False)
    popped = q.pop_pending()
    assert [t.request.request_id for t in popped] == ["b2"]
    assert q.depth() == 1
    delay = q.next_ready_delay()
    assert delay is not None and 25.0 < delay <= 30.0
    t1.not_before = 0.0
    assert [t.request.request_id for t in q.pop_pending()] == ["b1"]


def test_circuit_breaker_opens_and_half_open_recovers():
    """The breaker arc (docs/SERVING.md "SLOs and admission"): K=3
    consecutive injected batch errors open one program class — its
    pending requests reject fast with circuit-open while a healthy
    class keeps serving — and after the cooldown a single half-open
    probe recovers it."""
    from rocm_mpi_tpu.resilience import faults
    from rocm_mpi_tpu.resilience.policy import (
        CircuitPolicy,
        RequestRetryPolicy,
    )

    svc = SimulationService(config=ServeConfig(
        max_width=2,
        retry=RequestRetryPolicy(budget=1, backoff_base_s=0.0),
        circuit=CircuitPolicy(k=3, cooldown_drains=2),
    ))
    # Drain 1 executes the (16,16) class's three width-2 batches first
    # (sorted bin keys), then (24,24): the three errors strike exactly
    # the first class.
    faults.install(
        "batch-error@step=1;batch-error@step=2;batch-error@step=3"
    )
    try:
        sick, healthy = [], []
        for i in range(6):
            sick.append(svc.queue.submit(Request(
                request_id=f"sick-{i}", workload="diffusion",
                global_shape=(16, 16), dtype="f64", nt=3,
            )))
        for i in range(2):
            healthy.append(svc.queue.submit(Request(
                request_id=f"ok-{i}", workload="diffusion",
                global_shape=(24, 24), dtype="f64", nt=3,
            )))
        svc._drain_all()
        key = sbins.bin_key(sick[0].request)
        br = svc._breakers[key]
        assert br.state == "open"
        for t in healthy:
            assert t.state == "done", (t.request.request_id, t.error)
        # the open class rejected its (retried) tickets fast
        rejected = [t for t in sick if t.state == "rejected"]
        assert rejected and all(
            "circuit-open" in t.error for t in rejected
        )
        # cooldown passes as empty drains tick by
        svc.drain_once()
        svc.drain_once()
        probe = svc.queue.submit(Request(
            request_id="probe", workload="diffusion",
            global_shape=(16, 16), dtype="f64", nt=3,
        ))
        svc._drain_all()
        assert probe.state == "done", probe.error
        assert br.state == "closed"
        assert svc.queue.check_accounting() == []
    finally:
        faults.install(None)


def test_combined_chaos_drill(tmp_path, monkeypatch):
    """SATELLITE 3 — the combined chaos drill: one deterministic run
    with faults across all three layers — a queue-flood admission storm
    (grammar-driven), TWO NaN-poisoned lanes, a SIGTERM eviction at a
    batch boundary, and an injected storage outage on a session save —
    asserting the co-batched healthy tenants stay BITWISE-equal to
    their standalone twins and every submitted ticket is terminally
    accounted."""
    from rocm_mpi_tpu.resilience import faults
    from rocm_mpi_tpu.resilience.policy import RequestRetryPolicy
    from rocm_mpi_tpu.serving.queue import load_quarantine
    from rocm_mpi_tpu.utils import checkpoint as ckpt

    sessions = tmp_path / "sessions"
    qpath = tmp_path / "quarantine.jsonl"
    svc = SimulationService(config=ServeConfig(
        max_width=2, max_depth=8, sessions_dir=str(sessions),
        retry=RequestRetryPolicy(budget=1, backoff_base_s=0.0),
        quarantine_path=str(qpath),
    ))
    # Ordinals are 1-based submission numbers: 2 and 4 are the poison
    # lanes (times=9 outlasts the budget so they quarantine); the
    # session save at step 6 gets a 3-attempt io-error outage that
    # exhausts the checkpoint retry ladder once.
    faults.install(
        "lane-nan@request=2,times=9;lane-nan@request=4,times=9;"
        "io-error@step=6,times=3;queue-flood=8@step=2"
    )
    try:
        h0 = svc.queue.submit(Request(
            request_id="healthy-0", workload="diffusion",
            global_shape=(16, 16), dtype="f64", nt=5, ic_scale=1.1,
        ))
        p_a = svc.queue.submit(Request(
            request_id="poison-a", workload="diffusion",
            global_shape=(16, 16), dtype="f64", nt=5, ic_scale=1.7,
        ))
        h1 = svc.queue.submit(Request(
            request_id="healthy-1", workload="diffusion",
            global_shape=(16, 16), dtype="f64", nt=5, ic_scale=1.2,
        ))
        p_b = svc.queue.submit(Request(
            request_id="poison-b", workload="diffusion",
            global_shape=(16, 16), dtype="f64", nt=5, ic_scale=1.9,
        ))
        store = svc.queue.submit(Request(
            request_id="store", workload="diffusion",
            global_shape=(16, 16), dtype="f64", nt=6,
            session="chaos-s",
        ))
        h2 = svc.queue.submit(Request(
            request_id="healthy-2", workload="diffusion",
            global_shape=(16, 16), dtype="f64", nt=6, ic_scale=1.3,
        ))

        # The SIGTERM eviction lands at the SECOND batch boundary of
        # drain 1: batch one executes, the rest requeues (rc-75 shape).
        calls = {"n": 0}
        orig_preempt = svc._preempt_requested

        def evict_once():
            calls["n"] += 1
            return calls["n"] == 2

        monkeypatch.setattr(svc, "_preempt_requested", evict_once)

        flood_tickets = []
        drain = 0
        while True:
            drain += 1
            clause = faults.serving_fault("queue-flood", step=drain)
            if clause is not None:
                for i in range(int(clause.delay_s)):
                    flood_tickets.append(svc.queue.submit(Request(
                        request_id=f"flood-{i}", workload="diffusion",
                        global_shape=(16, 16), dtype="f64", nt=2,
                        ic_scale=1.0 + 0.01 * i,
                    )))
            _, preempted = svc.drain_once()
            if preempted:
                continue  # the eviction passed; next drain resumes
            if svc.queue.depth() == 0:
                break
            delay = svc.queue.next_ready_delay()
            if delay:
                time.sleep(min(delay, 0.25))
            assert drain < 60, "chaos drill did not converge"

        # (1) terminal accounting: every submitted ticket ended in
        # exactly one terminal state
        assert svc.queue.check_accounting() == []
        c = svc.queue.counters()
        assert c["quarantined"] == 2, c
        assert c["rejected"] >= 1, c  # the flood hit the depth bound
        assert c["requeued"] >= 1, c  # the eviction parked work

        # (2) the poison lanes — and ONLY they — were expelled
        assert p_a.state == "quarantined" and p_b.state == "quarantined"
        assert len(load_quarantine(qpath)) == 2

        # (3) the storage outage cost one lane retry, then a durable save
        assert store.state == "done" and store.retries >= 1
        assert ckpt.latest_valid_step(sessions / "chaos-s") == 6

        # (4) co-batched healthy tenants: bitwise-equal to standalone
        # twins despite sharing batches with NaN lanes, an eviction,
        # and a storage outage
        cfg = DiffusionConfig(global_shape=(16, 16), nt=8, warmup=0,
                              dtype="f64", dims=(1, 1))
        m = HeatDiffusion(cfg, devices=jax.devices()[:1])
        T0, Cp = m.init_state()
        adv = m.advance_fn("shard")
        for t in (h0, h1, h2):
            out = t.result(timeout=5)
            assert out is not None, (t.request.request_id, t.state)
            ref = np.asarray(adv(
                jnp.asarray(np.asarray(T0) * t.request.ic_scale), Cp,
                t.request.nt,
            ))
            assert np.array_equal(out[0], ref), t.request.request_id
        served_flood = [t for t in flood_tickets if t.state == "done"]
        assert served_flood, "the admitted flood slice was never served"
        monkeypatch.setattr(svc, "_preempt_requested", orig_preempt)
    finally:
        faults.install(None)


def test_service_preemption_requeues_and_reports(monkeypatch):
    """A preemption notice at a batch boundary stops dispatch; the
    unserved tickets are requeued (the scheduler's rc-75 signal)."""
    svc = SimulationService(config=ServeConfig(max_width=1))
    calls = {"n": 0}

    def notice_after_first():
        calls["n"] += 1
        return calls["n"] > 1  # first batch runs, then the notice lands

    monkeypatch.setattr(svc, "_preempt_requested", notice_after_first)
    trace = [
        Request(request_id=f"p{i}", workload="diffusion",
                global_shape=(16, 16), dtype="f64", nt=2 + i)
        for i in range(3)
    ]
    report = svc.run_trace(trace)
    assert report.preempted
    assert report.served == 1
    assert report.requeued == 2
    assert svc.queue.depth() == 2  # parked for the next service


def test_serve_forever_notices_preempt_between_drains():
    """Satellite: a preemption notice that lands while the daemon is
    IDLE-POLLING (between drain passes, nothing popped) must stop the
    loop immediately — requeue nothing, report preempted — instead of
    polling straight through its grace window to the scheduler's
    SIGKILL. Before the fix an idle daemon ignored the notice until
    idle_exit_s elapsed and then reported preempted=False."""
    from rocm_mpi_tpu.resilience import preempt

    svc = SimulationService(config=ServeConfig(max_width=2))
    warm = svc.run_trace([Request(
        request_id="warm", workload="diffusion",
        global_shape=(16, 16), dtype="f64", nt=2,
    )])
    assert warm.served == 1
    preempt.request()
    try:
        t0 = time.monotonic()
        report = svc.serve_forever(idle_exit_s=30.0)
        elapsed = time.monotonic() - t0
    finally:
        preempt.reset()
    assert report.preempted is True
    assert report.served == 0 and report.requeued == 0
    assert svc.queue.depth() == 0
    assert elapsed < 5.0  # noticed at the loop top, not after idle_exit_s


def test_service_elastic_grow_and_shrink():
    """The first real ElasticPolicy consumer: a deep queue grows the
    batch rows within the device budget (programs dropped, compile
    window reopened); idle drains shrink back to min_ranks."""
    from rocm_mpi_tpu.resilience.policy import ElasticPolicy

    svc = SimulationService(config=ServeConfig(
        max_width=4,
        policy=ElasticPolicy(min_grow_interval_steps=0),
        device_budget=lambda: 2,
        grow_queue_depth=4,
        idle_shrink_drains=2,
    ))
    for i in range(4):
        svc.queue.submit(Request(
            request_id=f"g{i}", workload="diffusion",
            global_shape=(16, 16), dtype="f64", nt=3,
        ))
    assert svc.maybe_resize()
    assert svc._batch_dims == 2
    report = svc._drain_all()
    assert report.served == 4
    assert [e["event"] for e in svc._elastic] == ["serve.grow"]
    # idle shrink: empty drains past the threshold fold the rows back
    svc.drain_once()
    svc.drain_once()
    assert svc.maybe_resize()
    assert svc._batch_dims == 1
    assert [e["event"] for e in svc._elastic] == \
        ["serve.grow", "serve.shrink"]


def test_serve_status_badge():
    from rocm_mpi_tpu.telemetry import health

    beats = {
        0: {"counters": {"serve_submitted": 20, "serve_completed": 17,
                         "serve_requeued": 0}},
    }
    st = health.serve_status(beats)
    assert st["depth"] == 3
    assert health.format_serve_status(st) == "[SERVE depth=3 — 17 done]"
    beats[0]["counters"]["serve_completed"] = 20
    beats[0]["counters"]["serve_resizes"] = 1
    assert health.format_serve_status(health.serve_status(beats)) == \
        "serve idle (20 done, 1 resize(s))"
    assert health.serve_status({0: {"counters": {"step": 3}}}) is None
    assert health.format_serve_status(None) is None
    # A FAILED request leaves the backlog too — it must not read as
    # depth forever.
    beats = {
        0: {"counters": {"serve_submitted": 5, "serve_completed": 4,
                         "serve_requeued": 0, "serve_failed": 1}},
    }
    st = health.serve_status(beats)
    assert st["depth"] == 0
    assert health.format_serve_status(st) == \
        "serve idle (4 done, 1 failed)"


def test_serve_badge_shows_slo_outcomes():
    """Satellite: a poisoned/overloaded service is visible from the
    heartbeat sidecar alone — deadline misses (expired), quarantined
    poison, rejections, and retries all ride the SERVE badge, and
    every terminal outcome (plus retry hand-backs) leaves the depth
    formula."""
    from rocm_mpi_tpu.telemetry import health

    beats = {
        0: {"counters": {
            "serve_submitted": 12, "serve_completed": 6,
            "serve_requeued": 0, "serve_failed": 0,
            "serve_expired": 2, "serve_quarantined": 1,
            "serve_rejected": 2, "serve_retries": 1,
        }},
    }
    st = health.serve_status(beats)
    assert st["depth"] == 0
    assert st["expired"] == 2 and st["quarantined"] == 1
    line = health.format_serve_status(st)
    assert line == ("serve idle (6 done, 2 deadline-missed, "
                    "1 quarantined, 2 rejected, 1 retried)")


def test_quarantine_schema_spelling_pinned_against_regress():
    """telemetry.regress spells the serving schema markers locally
    (stdlib read side) — drift from serving.queue must fail loudly."""
    from rocm_mpi_tpu.serving import queue as squeue
    from rocm_mpi_tpu.telemetry import regress

    assert regress._SERVE_REQUEST_SCHEMA == squeue.REQUEST_SCHEMA
    assert regress._QUARANTINE_SCHEMA == squeue.QUARANTINE_SCHEMA


def test_manifest_queue_counters_sum_invariant_gated(tmp_path):
    """Satellite: the archived manifest's queue block carries the
    terminal counters and the schema gate enforces that they sum to
    submissions — a leaked ticket fails the gate, not just the live
    assert."""
    from rocm_mpi_tpu.telemetry.regress import check_schema

    svc = SimulationService(config=ServeConfig(max_width=4))
    svc.run_trace(_mixed_trace("inv"))
    path = tmp_path / "serve-manifest.json"
    doc = svc.write_manifest(path)
    q = doc["queue"]
    for field in ("submitted", "completed", "failed", "rejected",
                  "expired", "quarantined", "depth"):
        assert isinstance(q[field], int), field
    assert check_schema([path]) == []
    # a leaked ticket (counters no longer sum) must fail the gate
    doc["queue"]["completed"] -= 1
    bad = tmp_path / "leaky-manifest.json"
    bad.write_text(json.dumps(doc))
    assert any("sum to submissions" in p for p in check_schema([bad]))


def test_session_save_failure_is_lane_isolated():
    """A lane whose session save cannot run (no sessions_dir) fails
    ONLY its ticket; the co-batched neighbor still resolves and the
    completion accounting stays exact."""
    svc = SimulationService(config=ServeConfig(max_width=2))
    bad = svc.queue.submit(Request(
        request_id="sv-bad", workload="diffusion",
        global_shape=(16, 16), dtype="f64", nt=2, session="s-x",
    ))
    good = svc.queue.submit(Request(
        request_id="sv-good", workload="diffusion",
        global_shape=(16, 16), dtype="f64", nt=2,
    ))
    report = svc._drain_all()
    assert report.failed == 1 and report.served == 1
    with pytest.raises(RuntimeError, match="sessions_dir"):
        bad.result(timeout=5)
    assert good.result(timeout=5) is not None


def test_non_pow2_batch_dims_rounds_down_instead_of_bricking():
    """--batch-dims 3 must never brick a pow2-width batch: the rows
    round down to a dividing power of two."""
    svc = SimulationService(config=ServeConfig(
        max_width=4, batch_dims=3,
    ))
    trace = [
        Request(request_id=f"bd{i}", workload="diffusion",
                global_shape=(16, 16), dtype="f64", nt=3)
        for i in range(4)
    ]
    report = svc.run_trace(trace)
    assert report.served == 4 and report.failed == 0
    assert all(p.endswith("|bd3") for p in report.programs)


def test_serve_app_trace_mode_honors_f64(tmp_path):
    """A RECORDED f64 trace enables x64 regardless of the synthetic
    --dtype knob: the session checkpoint's manifest must record
    float64 leaves, not silently-canonicalized float32."""
    import os

    trace_path = tmp_path / "trace.jsonl"
    req = Request(request_id="f64-1", workload="diffusion",
                  global_shape=(16, 16), dtype="f64", nt=4,
                  session="s64")
    trace_path.write_text(json.dumps(request_to_record(req)) + "\n")
    sessions = tmp_path / "sessions"
    proc = subprocess.run(
        [sys.executable, str(REPO / "apps" / "serve.py"),
         "--trace", str(trace_path), "--cpu-devices", "1",
         "--sessions", str(sessions)],
        capture_output=True, text=True, cwd=REPO, timeout=300,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 0, proc.stderr[-3000:]
    manifest = json.loads(
        (sessions / "s64" / "manifest-4.json").read_text()
    )
    assert manifest["leaves"][0]["dtype"] == "float64"


# ---------------------------------------------------------------------------
# The batched traffic audit
# ---------------------------------------------------------------------------


def test_batched_traffic_audit_within_budget():
    from rocm_mpi_tpu.perf import traffic

    rows = traffic.audit_batched(local=16, dims=(2, 1), batch=2)
    assert [r.variant for r in rows] == ["batched2", "batched-hide2",
                                         "ladder2"]
    for row in rows:
        assert row.wire_bytes == row.wire_ideal, (
            row.variant,
            "a batched exchange must ship EXACTLY B x the single-lane "
            "wire",
        )
        assert row.ok, (
            f"{row.variant} ratio {row.ratio:.2f} over budget"
        )
    # the hide row gates against its own committed tolerance
    assert rows[1].budget is not None and rows[1].budget >= 1.0


def test_batched_traffic_fixture_fails():
    """The doctored over-padded row (4 lanes compiled, 1 live) must
    fail — proof the audit catches the padding-inflation class the
    occupancy floor exists to split away."""
    from rocm_mpi_tpu.perf import traffic

    rows = traffic.audit_batched(local=16, dims=(2, 1), batch=2,
                                 include_batch_fixture=True)
    fixture = [r for r in rows if "fixture" in r.variant]
    assert len(fixture) == 1
    assert not fixture[0].ok
    assert fixture[0].ratio > fixture[0].budget


def test_perf_cli_batch_fixture_exits_1():
    proc = subprocess.run(
        [sys.executable, "-m", "rocm_mpi_tpu.perf",
         "--include-batch-fixture", "--no-wire", "--local", "16"],
        capture_output=True, text=True, cwd=REPO, timeout=600,
        env={**__import__("os").environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 1, proc.stderr
    assert "TRAFFIC GATE FAILED" in proc.stderr


def test_budgets_serving_block_schema_gate(tmp_path):
    from rocm_mpi_tpu.perf.traffic import load_budgets
    from rocm_mpi_tpu.telemetry.regress import check_schema

    doc = load_budgets()
    assert doc["serving"]["batch_tolerance"] >= 1.0
    doc["serving"]["occupancy_floor"] = 1.7
    bad = tmp_path / "budgets.json"
    bad.write_text(json.dumps(doc))
    assert any("occupancy_floor" in p for p in check_schema([bad]))


# ---------------------------------------------------------------------------
# The drain pipeline (ISSUE 15, docs/SERVING.md "The pipeline")
# ---------------------------------------------------------------------------


def test_diffusion_batched_hide_parity_heterogeneous_steps():
    """The lane-batched comm/compute overlap (variant "hide" through
    make_batched_overlap_step): every lane bitwise-equal to a
    standalone hide run of its own length — the paper's overlap
    tentpole at batch scale keeps the serving parity contract."""
    B = 4
    cfg = DiffusionConfig(global_shape=(16, 16), nt=8, warmup=0,
                          dtype="f64", dims=(1, 2))
    m = HeatDiffusion(cfg, devices=jax.devices()[:2])
    adv_b, bg = m.batched_advance_fn(batch=B, batch_dims=2,
                                     variant="hide")
    T0, Cp = m.init_state()
    lanes = np.stack(
        [np.asarray(T0) * (1 + 0.1 * i) for i in range(B)]
    )
    out = np.asarray(adv_b(
        _put(lanes, bg.sharding),
        _put(Cp, bg.aux_sharding),
        _put(np.array(LANE_STEPS, np.int32), bg.batch_sharding),
        max(LANE_STEPS),
    ))
    adv1 = m.advance_fn("hide")
    for i in range(B):
        ref = np.asarray(adv1(
            _put(lanes[i], m.grid.sharding), Cp, LANE_STEPS[i]
        ))
        assert np.array_equal(out[i], ref), f"lane {i}"


def test_service_serves_batched_hide_variant():
    """A variant="hide" request class compiles the lane-batched
    overlap program and serves bitwise-equal to a standalone hide run
    on the same space decomposition."""
    compiles.install()
    # Earlier tests' model-level compiles land inside THEIR services'
    # steady windows; this assertion is about this service alone.
    compiles.reset()
    svc = SimulationService(config=ServeConfig(max_width=4))
    reqs = [
        Request(request_id=f"hide-{i}", workload="diffusion",
                global_shape=(16, 16), dtype="f64", nt=4 + i,
                variant="hide", ic_scale=1.0 + 0.1 * i)
        for i in range(3)
    ]
    tickets = [svc.queue.submit(r) for r in reqs]
    report = svc._drain_all()
    assert report.served == 3 and report.failed == 0
    assert report.compiles["steady_state"] == 0
    assert all("|hide|" in p for p in report.programs)

    space_dims = pmesh.plan_dims((16, 16), len(jax.devices()))
    cfg = DiffusionConfig(global_shape=(16, 16), nt=16, warmup=0,
                          dtype="f64", dims=space_dims)
    m = HeatDiffusion(cfg)
    T0, Cp = m.init_state()
    adv = m.advance_fn("hide")
    for i, t in enumerate(tickets):
        out = t.result(timeout=5)
        ref = np.asarray(adv(
            _put(np.asarray(T0) * reqs[i].ic_scale, m.grid.sharding),
            Cp, reqs[i].nt,
        ))
        assert np.array_equal(out[0], ref), f"request {i}"


def test_pipelined_drain_bitwise_equal_to_serial(tmp_path):
    """THE pipeline acceptance: the same heterogeneous trace — three
    workloads, mixed steps, a session save, an injected transient
    batch error riding the retry budget — through the serial (depth 1)
    and double-buffered (depth 2) drains books IDENTICAL queue
    counters, bitwise-identical results per request, and
    bitwise-identical durable session checkpoints."""
    from rocm_mpi_tpu.resilience import faults
    from rocm_mpi_tpu.resilience.policy import RequestRetryPolicy
    from rocm_mpi_tpu.utils import checkpoint as ckpt

    outs, counters, saved = {}, {}, {}
    for depth in (1, 2):
        sessions = tmp_path / f"sessions{depth}"
        svc = SimulationService(config=ServeConfig(
            max_width=4, pipeline_depth=depth,
            sessions_dir=str(sessions),
            retry=RequestRetryPolicy(budget=2, backoff_base_s=0.0),
        ))
        trace = _mixed_trace(f"pp{depth}")
        trace.append(Request(
            request_id=f"pp{depth}-sess", workload="diffusion",
            global_shape=(16, 16), dtype="f64", nt=4, ic_scale=1.2,
            session="pp-sess",
        ))
        tickets = [svc.queue.submit(r) for r in trace]
        faults.install("batch-error@step=2")
        try:
            report = svc._drain_all()
        finally:
            faults.install(None)
        assert report.failed == 0 and report.quarantined == 0
        assert svc.queue.check_accounting() == []
        counters[depth] = {
            k: v for k, v in svc.queue.counters().items()
            if k != "depth"
        }
        assert counters[depth]["requeued"] >= 1, \
            "the injected batch error never exercised the retry path"
        outs[depth] = [t.result(timeout=5) for t in tickets]
        saved[depth] = np.asarray(
            ckpt.restore_state(sessions / "pp-sess", 4, like=None)[0]
        )
    assert counters[1] == counters[2], (
        "pipelined drain reordered terminal accounting"
    )
    for i, (a, b) in enumerate(zip(outs[1], outs[2])):
        for la, lb in zip(a, b):
            assert np.array_equal(np.asarray(la), np.asarray(lb)), (
                f"request {i}: pipelined != serial"
            )
    assert np.array_equal(saved[1], saved[2])


def test_pipelined_prepare_failure_retries_then_serves():
    """Pipelined edition of the transient-batch-failure contract: an
    injected batch-error at the PREPARE (dispatch-side) stage requeues
    the batch's tickets through the retry budget; the retried batch
    serves them — no stranded 'running' tickets, invariant holds."""
    from rocm_mpi_tpu.resilience import faults
    from rocm_mpi_tpu.resilience.policy import RequestRetryPolicy

    svc = SimulationService(config=ServeConfig(
        max_width=1, pipeline_depth=2,
        retry=RequestRetryPolicy(budget=2, backoff_base_s=0.0),
    ))
    t1 = svc.queue.submit(Request(
        request_id="pf1", workload="diffusion", global_shape=(16, 16),
        dtype="f64", nt=2,
    ))
    t2 = svc.queue.submit(Request(
        request_id="pf2", workload="diffusion", global_shape=(16, 16),
        dtype="f64", nt=3,
    ))
    faults.install("batch-error@step=1")
    try:
        report = svc._drain_all()
    finally:
        faults.install(None)
    assert report.failed == 0 and report.served == 2
    assert t1.retries == 1 and t1.state == "done"
    assert t2.state == "done"
    assert svc.queue.check_accounting() == []


def test_retry_after_dispatched_batch_never_reads_donated_buffer(
        monkeypatch):
    """THE async-dispatch/donation hazard drill: a batch that fails
    AFTER dispatch (at the fetch/resolve stage) retries by
    re-assembling from HOST state — the donated device buffers were
    consumed by the advance and are never re-read (a re-read would
    raise jax's deleted-array error), and the retried result stays
    bitwise-equal to a standalone run."""
    from rocm_mpi_tpu.resilience.policy import RequestRetryPolicy

    svc = SimulationService(config=ServeConfig(
        max_width=1, pipeline_depth=2,
        retry=RequestRetryPolicy(budget=2, backoff_base_s=0.0),
    ))
    orig = svc._resolve_batch
    calls = {"n": 0}

    def flaky_resolve(fl):
        calls["n"] += 1
        if calls["n"] == 1:
            raise RuntimeError("device fault surfacing at fetch")
        return orig(fl)

    monkeypatch.setattr(svc, "_resolve_batch", flaky_resolve)
    t = svc.queue.submit(Request(
        request_id="donate-1", workload="diffusion",
        global_shape=(16, 16), dtype="f64", nt=5, ic_scale=1.3,
    ))
    report = svc._drain_all()
    assert report.failed == 0 and report.served == 1
    assert t.state == "done" and t.retries == 1
    out = t.result(timeout=5)
    cfg = DiffusionConfig(global_shape=(16, 16), nt=8, warmup=0,
                          dtype="f64", dims=(1, 1))
    m = HeatDiffusion(cfg, devices=jax.devices()[:1])
    T0, Cp = m.init_state()
    ref = np.asarray(m.advance_fn("shard")(
        jnp.asarray(np.asarray(T0) * 1.3), Cp, 5
    ))
    assert np.array_equal(out[0], ref)
    assert svc.queue.check_accounting() == []


def test_pipelined_same_drain_save_then_resume_matches_serial(tmp_path):
    """The session read-after-write barrier: request A saves session
    's' and request B resumes 's' in SEPARATE batches of ONE drain
    pass. The pipelined drain must flush A's resolve (the save) before
    assembling B — B resumes from step 4 in both modes and the two-leg
    result stays bitwise-equal to the serial drain's."""
    outs, starts = {}, {}
    for depth in (1, 2):
        sessions = tmp_path / f"sessions{depth}"
        svc = SimulationService(config=ServeConfig(
            max_width=1, pipeline_depth=depth,
            sessions_dir=str(sessions),
        ))
        a = svc.queue.submit(Request(
            request_id=f"rw{depth}-a", workload="diffusion",
            global_shape=(16, 16), dtype="f64", nt=4, ic_scale=1.1,
            session="rw-sess",
        ))
        b = svc.queue.submit(Request(
            request_id=f"rw{depth}-b", workload="diffusion",
            global_shape=(16, 16), dtype="f64", nt=9, ic_scale=1.1,
            session="rw-sess", resume=True,
        ))
        report = svc._drain_all()
        assert report.failed == 0 and report.served == 2
        assert a.state == "done" and b.state == "done"
        starts[depth] = (b.start_step, b.steps_run)
        outs[depth] = np.asarray(b.result(timeout=5)[0])
    assert starts[1] == (4, 5), starts
    assert starts[2] == (4, 5), (
        "the pipelined drain assembled the resume lane before the "
        f"same-drain session save landed: {starts}"
    )
    assert np.array_equal(outs[1], outs[2])


def test_failing_dispatch_hook_cannot_wedge_bubble_accounting():
    """A stage hook that raises at the dispatch stage must not leave
    the in-flight counter stuck high (which would freeze busy_s and
    report a forever-1.0 bubble): the batch fails through the normal
    routing and the NEXT drain's accounting still moves."""
    from rocm_mpi_tpu.resilience.policy import RequestRetryPolicy

    calls = {"n": 0}

    def exploding_once(stage, info):
        calls["n"] += 1
        if calls["n"] == 1:
            raise RuntimeError("hook blew up at dispatch")

    svc = SimulationService(config=ServeConfig(
        max_width=1, pipeline_depth=2,
        retry=RequestRetryPolicy(budget=2, backoff_base_s=0.0),
        stage_hooks={"dispatch": exploding_once},
    ))
    t = svc.queue.submit(Request(
        request_id="hook-1", workload="diffusion",
        global_shape=(16, 16), dtype="f64", nt=3,
    ))
    report = svc._drain_all()
    assert t.state == "done" and t.retries == 1 and report.failed == 0
    assert svc._inflight_n == 0, "in-flight counter leaked"
    assert svc._pipe["busy_s"] > 0.0, (
        "busy accounting froze after the failed dispatch hook"
    )
    assert svc.queue.check_accounting() == []


def _drain_wall(depth: int, nt: int, sleep_s: float, tag: str):
    """One measured drain: 4 one-lane batches of the same bin, program
    cache warmed first so the clock sees the steady state. The resolve
    hook injects the deterministic slow host stage."""
    hooks = None
    if sleep_s:
        hooks = {"resolve": lambda stage, info: time.sleep(sleep_s)}
    svc = SimulationService(config=ServeConfig(
        max_width=1, pipeline_depth=depth, stage_hooks=hooks,
    ))

    def req(rid, scale=1.0):
        return Request(request_id=rid, workload="diffusion",
                       global_shape=(64, 64), dtype="f64", nt=nt,
                       ic_scale=scale)

    svc.run_trace([req(f"{tag}-warm")])
    for i in range(4):
        svc.queue.submit(req(f"{tag}-{i}", 1.0 + 0.01 * i))
    t0 = time.monotonic()
    report = svc._drain_all()
    wall = time.monotonic() - t0
    assert report.served == 4, report
    return wall, svc


def test_pipelined_drain_hides_slow_host_stage():
    """The pipeline win, measured: with a deterministically slow host
    resolve stage (stage hook), the double-buffered drain's wall is
    measurably below the serial drain's — the device computes batch
    N+1 while the host resolves batch N — and the device-bubble gauge
    agrees (pipelined bubble < serial bubble)."""
    # Calibrate per-batch compute+overhead wall; scale the step count
    # up on very fast machines so the hideable device work is
    # non-trivial vs timer noise (n is a dynamic trip count — scaling
    # it recompiles nothing within a steps bucket's program).
    nt = 512
    wall0, _ = _drain_wall(1, nt, 0.0, "cal")
    c = wall0 / 4
    if c < 0.04:
        nt = min(int(nt * 0.05 / max(c, 1e-4)), 16384)
        wall0, _ = _drain_wall(1, nt, 0.0, "cal2")
        c = wall0 / 4
    sleep_s = max(1.5 * c, 0.05)
    serial_wall, serial_svc = _drain_wall(1, nt, sleep_s, "ser")
    pipe_wall, pipe_svc = _drain_wall(2, nt, sleep_s, "pipe")
    # Expected savings ~= (batches-1+) x c (the compute hidden under
    # the host stage); require a 1.5c margin — generous vs the ~3.5c
    # expectation, robust to CI noise.
    assert pipe_wall < serial_wall - 1.5 * c, (
        f"pipelined drain hid nothing: serial {serial_wall:.3f}s, "
        f"pipelined {pipe_wall:.3f}s, per-batch compute {c:.3f}s"
    )
    assert pipe_svc.pipeline_stats()["bubble"] \
        < serial_svc.pipeline_stats()["bubble"], (
        serial_svc.pipeline_stats(), pipe_svc.pipeline_stats(),
    )


def test_manifest_pipeline_block_and_schema_gate(tmp_path):
    """The manifest's pipeline block (depth, batches, bubble, stage
    walls) validates — and a doctored bubble/depth fails the schema
    gate, not silently corrupts a pipeline-efficiency audit."""
    svc = SimulationService(config=ServeConfig(max_width=4))
    svc.run_trace(_mixed_trace("pipe-man"))
    path = tmp_path / "serve-manifest.json"
    doc = svc.write_manifest(path)
    pipe = doc["pipeline"]
    assert pipe["depth"] == 2 and pipe["batches"] >= 1
    assert 0.0 <= pipe["bubble"] <= 1.0
    for field in ("assemble_s", "dispatch_s", "fetch_s", "resolve_s"):
        assert pipe[field] >= 0.0
    assert sbins.validate_manifest_doc(doc) == []

    from rocm_mpi_tpu.telemetry.regress import check_schema

    assert check_schema([path]) == []
    doc["pipeline"]["bubble"] = 1.7
    bad = tmp_path / "bad-manifest.json"
    bad.write_text(json.dumps(doc))
    assert any("bubble" in p for p in check_schema([bad]))
    doc["pipeline"]["bubble"] = 0.1
    doc["pipeline"]["depth"] = 0
    bad.write_text(json.dumps(doc))
    assert any("depth" in p for p in check_schema([bad]))


def test_pipeline_gauges_learned_by_regress():
    """serve.device_bubble is lower-is-better WITH zero as evidence
    (the fully-overlapped contract — a zero baseline makes any bubble
    growth a gated regression); serve.pipeline_depth is a config echo
    and never regress-gated."""
    from rocm_mpi_tpu.telemetry.regress import compare, extract_metrics

    doc = {"gauges": {"serve.device_bubble": 0.0,
                      "serve.pipeline_depth": 2.0,
                      "run.gpts@1dev": 5.0}}
    m = extract_metrics(doc)
    assert m["gauges.serve.device_bubble"] == (0.0, "lower")
    assert "gauges.serve.pipeline_depth" not in m
    base = {"gauges": {"serve.device_bubble": 0.0}}
    cur = {"gauges": {"serve.device_bubble": 0.25}}
    assert any(d.regressed for d in compare(cur, base))


def test_lowered_audit_proves_batched_donation():
    """Tentpole (b)'s proof: every batched advance's declared donation
    — diffusion's one leaf (shard AND hide), wave's two leapfrog
    carries, SWE's h + velocity leaves — actually aliased in the
    compiled program's input_output_alias table, and the batched
    collectives stay per-space-axis partial permutations outside any
    lowered conditional."""
    from rocm_mpi_tpu.analysis import lowered

    rows = lowered.audit_batched_drivers(local=8, batch=2)
    by_name = {r.workload: r for r in rows}
    assert set(by_name) == {
        "diffusion/batched-shard", "diffusion/batched-hide",
        "wave/batched", "swe/batched",
    }
    for r in rows:
        assert r.ok, (r.workload, r.problems)
        assert r.n_collectives >= 1
    assert by_name["diffusion/batched-shard"].donated_params == 1
    assert by_name["diffusion/batched-hide"].donated_params == 1
    assert by_name["wave/batched"].donated_params == 2
    assert by_name["swe/batched"].donated_params == 3


# ---------------------------------------------------------------------------
# Acceptance drills
# ---------------------------------------------------------------------------


def test_serve_app_50_request_acceptance(tmp_path):
    """THE acceptance drill: a heterogeneous 50-request trace (3 shape
    classes, mixed physics/workloads/steps) through apps/serve.py
    compiles exactly len(bins) programs (manifest-pinned) with
    compiles.steady_state == 0, and the banked sidecars clear the
    schema gate."""
    out = tmp_path / "out"
    proc = subprocess.run(
        [sys.executable, str(REPO / "apps" / "serve.py"),
         "--synthetic", "50", "--seed", "3", "--nt-max", "16",
         "--max-width", "4", "--cpu-devices", "1",
         "--out", str(out)],
        capture_output=True, text=True, cwd=REPO, timeout=900,
        env={**__import__("os").environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 0, proc.stderr[-3000:]
    assert "compiles.steady_state=0" in proc.stdout
    doc = json.loads((out / "serve-manifest.json").read_text())
    assert sbins.validate_manifest_doc(doc) == []
    assert doc["served"] == 50 and doc["preempted"] is False
    assert doc["compiles"]["steady_state"] == 0
    assert len(doc["bins"]) >= 3
    # exactly len(bins) programs: every program class belongs to a bin,
    # and every bin's width classes are all present
    widths = sum(len(row["widths"]) for row in doc["bins"])
    assert len(doc["programs"]) == widths
    shapes = {row["key"].split("|")[1] for row in doc["bins"]}
    assert len(shapes) >= 3

    from rocm_mpi_tpu.telemetry.regress import check_schema

    assert check_schema([out / "serve-manifest.json",
                         out / "serve-requests.jsonl"]) == []


def test_serve_daemon_sigterm_while_idle_exits_75(tmp_path):
    """Satellite: THE missing daemon drill — apps/serve.py --serve
    drains its trace, idles, and then a real SIGTERM lands BETWEEN
    drain passes. The daemon must exit rc 75 promptly (not poll
    through its grace window), requeue nothing (nothing was popped),
    and still bank a schema-valid manifest on the way out."""
    out = tmp_path / "out"
    tele = tmp_path / "tele"
    proc = subprocess.Popen(
        [sys.executable, str(REPO / "apps" / "serve.py"),
         "--serve", "--idle-exit-s", "300",
         "--synthetic", "3", "--seed", "7", "--nt-max", "3",
         "--max-width", "4", "--cpu-devices", "1",
         "--telemetry", str(tele), "--out", str(out)],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        cwd=REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu",
             "RMT_PREEMPT_GRACE_S": "30"},
    )
    try:
        # wait until the trace is fully drained (the daemon is now
        # idle-polling) by watching the live telemetry stream
        stream = tele / "telemetry-rank0.jsonl"
        deadline = time.monotonic() + 300.0
        while time.monotonic() < deadline:
            done = 0
            if stream.is_file():
                done = stream.read_text(errors="replace").count(
                    "serve.request.done")
            if done >= 3:
                break
            assert proc.poll() is None, proc.communicate()
            time.sleep(0.2)
        else:
            raise AssertionError("daemon never drained its trace")
        proc.send_signal(signal.SIGTERM)
        stdout, stderr = proc.communicate(timeout=120)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.communicate()
    assert proc.returncode == 75, (proc.returncode, stdout[-2000:],
                                   stderr[-2000:])
    assert "rc 75" in stdout
    assert "0 requeued" in stdout  # idle notice: nothing was popped
    doc = json.loads((out / "serve-manifest.json").read_text())
    assert doc["preempted"] is True and doc["served"] == 3

    from rocm_mpi_tpu.telemetry.regress import check_schema

    assert check_schema([out / "serve-manifest.json",
                         out / "serve-requests.jsonl"]) == []


def test_serving_gloo_two_rank_drill(tmp_path):
    """Gloo-real 2-rank drill: a heterogeneous queue served by a
    2-rank space mesh compiles exactly len(bins) programs on every
    rank, with compiles.steady_state == 0 and a second identical trace
    compiling NOTHING (tests/serving_worker.py)."""
    from rocm_mpi_tpu.parallel.launcher import spawn_ranks

    results = spawn_ranks(
        [REPO / "tests" / "serving_worker.py"], nprocs=2, timeout=420,
    )
    for rank, (proc, (out, err)) in enumerate(results):
        assert proc.returncode == 0, (rank, out[-500:], err[-2000:])
        done = [l for l in out.splitlines()
                if "SERVING_WORKER_DONE" in l]
        assert len(done) == 1, out
        line = done[0]
        assert f"rank={rank}" in line
        assert "bins=4 programs=4" in line, line
        assert "steady=0" in line and "second_trace_compiles=0" in line
