"""Request-scoped distributed tracing (docs/TELEMETRY.md "Request
tracing"): context wire round-trips and the v3 request schema, clock
anchors + cross-replica alignment (with the legacy-stream warning),
the telescoping latency decomposition summing to the done latency on
both the classic and the segmented drain, the `telemetry trace` CLI
verb + rmt-trace-report schema gate, the flight-recorder in-flight
roster, the SLO decomposition aggregate, and the tracing-off switch
(the bench overhead rung's second arm)."""

from __future__ import annotations

import json
import pathlib

import pytest

from rocm_mpi_tpu.serving.queue import (
    REQUEST_VERSION,
    Request,
    request_from_record,
    request_to_record,
    validate_request_record,
)
from rocm_mpi_tpu.telemetry import (
    aggregate,
    events,
    flight,
    regress,
    tracing,
)
from rocm_mpi_tpu.telemetry.__main__ import main as cli_main

REPO = pathlib.Path(__file__).resolve().parent.parent


@pytest.fixture(autouse=True)
def _isolated_telemetry(monkeypatch):
    """Telemetry and the flight recorder start disabled and empty
    (the test_telemetry/test_health convention)."""
    monkeypatch.setattr(events, "_ENABLED", False)
    monkeypatch.setattr(events, "_DIR", None)
    monkeypatch.setattr(events, "_RANK", None)
    events.clear()
    monkeypatch.setattr(flight, "_ENABLED", False)
    flight.reset()
    yield
    events.clear()
    flight.disable()
    flight.reset()


def _req(rid, nt=4, shape=(16, 16), **kw):
    return Request(request_id=rid, workload="diffusion",
                   global_shape=shape, dtype="f32", nt=nt, **kw)


# ---------------------------------------------------------------------------
# Contexts and the v3 request schema
# ---------------------------------------------------------------------------


def test_context_mint_child_hop_and_wire_roundtrip():
    root = tracing.mint("req-1")
    assert root.trace_id == "req-1" and root.hop == 0
    assert root.parent_id is None

    c = tracing.child(root)
    assert c.parent_id == root.span_id and c.hop == 0
    assert c.span_id != root.span_id

    h = tracing.next_hop(c)
    assert h.hop == 1 and h.parent_id == c.span_id
    assert h.trace_id == "req-1", "trace_id IS the request_id, always"

    wire = tracing.to_wire(h)
    assert tracing.validate_wire(wire) == []
    back = tracing.from_wire(wire)
    assert back == h
    assert tracing.to_wire(None) is None
    assert tracing.from_wire(None) is None
    assert tracing.from_wire({"trace_id": "x"}) is None  # no span_id


def test_validate_wire_names_each_problem():
    bad = {"trace_id": "", "span_id": 3, "hop": -1, "parent_id": 7}
    problems = tracing.validate_wire(bad)
    assert len(problems) == 4, problems
    assert tracing.validate_wire("nope") != []


def test_request_record_v3_trace_roundtrip():
    assert REQUEST_VERSION == 3
    ctx = tracing.mint("rt-1")
    r = _req("rt-1", trace=tracing.to_wire(ctx))
    doc = request_to_record(r)
    assert doc["v"] == REQUEST_VERSION
    assert validate_request_record(doc) == []
    back = request_from_record(doc)
    assert back.trace == tracing.to_wire(ctx)

    # trace-less requests (and legacy v2 records) stay valid — the
    # field is optional, not a flag day
    plain = request_to_record(_req("rt-2"))
    assert "trace" not in plain
    assert request_from_record(plain).trace is None

    doc_bad = dict(doc, trace={"trace_id": 1})
    assert validate_request_record(doc_bad) != []


# ---------------------------------------------------------------------------
# Clock anchors and alignment
# ---------------------------------------------------------------------------


def test_configure_emits_one_anchor_first(tmp_path):
    events.configure(directory=tmp_path, rank=1)
    events.record_event("x.y", step=1)
    events.configure(directory=tmp_path, rank=1)  # idempotent
    lines = [json.loads(s) for s in
             (tmp_path / "telemetry-rank1.jsonl").read_text()
             .splitlines()]
    anchors = [r for r in lines if r["kind"] == tracing.ANCHOR_KIND]
    assert len(anchors) == 1 and lines[0] is not None
    assert lines[0]["kind"] == tracing.ANCHOR_KIND
    assert lines[0]["name"] == tracing.ANCHOR_NAME
    assert tracing.anchor_of(lines) == (
        lines[0]["t"], lines[0]["t_mono"]
    )


def test_aligned_wall_maps_monotonic_through_the_anchor():
    anchor = (1000.0, 10.0)
    rec = {"t": 5555.5, "t_mono": 12.5}
    assert tracing.aligned_wall(rec, anchor) == pytest.approx(1002.5)
    # legacy: no anchor -> the record's own wall stamp
    assert tracing.aligned_wall(rec, None) == pytest.approx(5555.5)
    assert tracing.aligned_wall({"name": "x"}, None) is None


def test_request_timeline_aligns_ranks_and_warns_on_legacy():
    # rank 0: anchored, wall clock skewed far from rank 1's; rank 1:
    # legacy (no anchor). The timeline must order rank 0's rows on the
    # anchor-mapped clock and name rank 1's stream in a warning.
    streams = {
        0: [
            {"kind": "anchor", "name": "clock.anchor",
             "t": 1000.0, "t_mono": 10.0},
            {"kind": "tspan", "name": "trace.submit",
             "trace_id": "q-1", "span_id": "s0.1", "hop": 0,
             "t": 999999.0, "t_mono": 11.0},
        ],
        1: [
            {"kind": "event", "name": "serve.request.done",
             "request_id": "q-1", "latency_s": 0.5, "hop": 0,
             "decomp": {"queue_wait": 0.1, "device": 0.4},
             "t": 1003.0, "t_mono": 77.0},
        ],
    }
    tl = tracing.request_timeline(streams, "q-1")
    assert tl is not None
    assert [r["name"] for r in tl["events"]] \
        == ["trace.submit", "serve.request.done"]
    assert tl["events"][0]["t"] == pytest.approx(1001.0), \
        "anchored rank must use anchor_t + (t_mono - anchor_t_mono)"
    assert tl["terminal"] == "done" and tl["hops"] == [0]
    assert tl["latency_s"] == pytest.approx(0.5)
    assert len(tl["warnings"]) == 1 and "rank 1" in tl["warnings"][0]
    assert tracing.request_timeline(streams, "nobody") is None


# ---------------------------------------------------------------------------
# End-to-end: drains decompose latency (classic and segmented)
# ---------------------------------------------------------------------------


def _timelines_after(svc, reqs, tmp_dir):
    tickets = [svc.queue.submit(r) for r in reqs]
    svc._drain_all()
    streams, _ = aggregate.load_rank_streams(tmp_dir)
    out = {}
    for t in tickets:
        rid = t.request.request_id
        out[rid] = tracing.request_timeline(streams, rid)
    return tickets, streams, out


def test_classic_drain_decomposition_sums_to_latency(tmp_path):
    from rocm_mpi_tpu.serving.service import (
        ServeConfig,
        SimulationService,
    )

    events.configure(directory=tmp_path, rank=0)
    svc = SimulationService(config=ServeConfig(max_width=2))
    reqs = [_req(f"cl-{i}", nt=3 + i % 2, ic_scale=1.0 + 0.01 * i)
            for i in range(4)]
    _, streams, timelines = _timelines_after(svc, reqs, tmp_path)

    for rid, tl in timelines.items():
        assert tl is not None and tl["terminal"] == "done", rid
        assert tl["hops"] == [0]
        assert not tl["warnings"], tl["warnings"]
        decomp = tl["decomposition"]
        assert decomp is not None
        assert tracing.validate_decomposition(decomp) == []
        assert set(decomp) <= set(tracing.DECOMP_STAGES)
        # the telescoping contract: stages sum to the done latency
        assert sum(decomp.values()) \
            == pytest.approx(tl["latency_s"], abs=0.02), (rid, decomp)
        names = [r["name"] for r in tl["events"]]
        assert "trace.submit" in names and "trace.batch" in names

    # the batch roster makes every member findable without per-lane
    # tspans: O(batches) stream growth is the design point
    recs = streams[0]
    batch_recs = [r for r in recs if r.get("name") == "trace.batch"]
    assert batch_recs
    rostered = {m["trace_id"] for r in batch_recs
                for m in r.get("members", ())}
    assert rostered == {r.request_id for r in reqs}


def test_segmented_drain_decomposition_and_segment_roster(tmp_path):
    from rocm_mpi_tpu.serving.service import (
        ServeConfig,
        SimulationService,
    )

    events.configure(directory=tmp_path, rank=0)
    svc = SimulationService(config=ServeConfig(
        max_width=2, segments=2,
    ))
    # 3 same-class requests through 2 lanes: the third swaps into a
    # freed lane at a segment boundary and must inherit the segment
    # roster it joined at
    reqs = [_req(f"sg-{i}", nt=4, ic_scale=1.0 + 0.01 * i)
            for i in range(3)]
    _, streams, timelines = _timelines_after(svc, reqs, tmp_path)

    for rid, tl in timelines.items():
        assert tl is not None and tl["terminal"] == "done", rid
        decomp = tl["decomposition"]
        assert decomp is not None
        assert tracing.validate_decomposition(decomp) == []
        assert sum(decomp.values()) \
            == pytest.approx(tl["latency_s"], abs=0.02), (rid, decomp)

    seg_recs = [r for r in streams[0]
                if r.get("name") == "trace.segment"]
    assert seg_recs, "segmented drain must emit boundary tspans"
    rostered = {m["trace_id"] for r in seg_recs
                for m in r.get("members", ())}
    assert "sg-2" in rostered, "the swapped-in lane joins the roster"


def test_tracing_off_is_silent_and_decomp_free(tmp_path):
    from rocm_mpi_tpu.serving.service import (
        ServeConfig,
        SimulationService,
    )

    events.configure(directory=tmp_path, rank=0)
    svc = SimulationService(config=ServeConfig(
        max_width=2, trace_requests=False,
    ))
    tickets = [svc.queue.submit(_req(f"off-{i}")) for i in range(2)]
    svc._drain_all()
    assert all(t.state == "done" for t in tickets)
    recs, _ = aggregate.load_rank_streams(tmp_path)
    stream = recs[0]
    done = [r for r in stream if r.get("name") == "serve.request.done"]
    assert done and all("decomp" not in r and "hop" not in r
                        for r in done)
    batchy = [r for r in stream if r.get("kind") == tracing.TRACE_KIND
              and r.get("name") in ("trace.batch", "trace.segment")]
    assert batchy == [], "the drain hot path must emit no batch tspans"


# ---------------------------------------------------------------------------
# CLI verb + report schema gate
# ---------------------------------------------------------------------------


def test_trace_cli_report_and_chrome(tmp_path, capsys):
    from rocm_mpi_tpu.serving.service import (
        ServeConfig,
        SimulationService,
    )

    tdir = tmp_path / "telemetry"
    events.configure(directory=tdir, rank=0)
    svc = SimulationService(config=ServeConfig(max_width=2))
    _timelines_after(svc, [_req("cli-0"), _req("cli-1")], tdir)

    report = tmp_path / "trace-report-cli-0.json"
    chrome = tmp_path / "trace-cli-0.json"
    rc = cli_main(["trace", str(tdir), "--request", "cli-0",
                   "--out", str(report), "--chrome", str(chrome)])
    assert rc == 0
    out = capsys.readouterr().out
    assert "trace cli-0" in out and "serve.request.done" in out

    doc = json.loads(report.read_text())
    assert tracing.validate_trace_report(doc) == []
    # the regress schema gate classifies and validates the artifact
    assert regress.check_schema([report]) == []

    cdoc = json.loads(chrome.read_text())
    assert cdoc["traceEvents"], "chrome export must carry events"

    # unknown request: exit 2 (missing-input contract, not a crash)
    assert cli_main(["trace", str(tdir), "--request", "ghost"]) == 2
    assert cli_main(["trace", str(tmp_path / "void"),
                     "--request", "x"]) == 2


def test_regress_gates_done_event_decomp(tmp_path):
    # a done event with a corrupt decomposition must fail the stream
    # schema check (the PR-20 guarded-event extension)
    stream = tmp_path / "telemetry-rank0.jsonl"
    good = {"v": 2, "kind": "event", "name": "serve.request.done",
            "t": 1.0, "t_mono": 1.0, "rank": 0, "request_id": "a",
            "latency_s": 0.1, "decomp": {"queue_wait": 0.1}}
    bad = dict(good, decomp={"not_a_stage": 0.1})
    stream.write_text(json.dumps(good) + "\n")
    assert regress.check_schema([stream]) == []
    stream.write_text(json.dumps(bad) + "\n")
    assert regress.check_schema([stream]) != []


# ---------------------------------------------------------------------------
# Flight-recorder roster, SLO aggregate, summary counters
# ---------------------------------------------------------------------------


def test_flight_snapshot_carries_inflight_traces(tmp_path):
    flight.enable(directory=tmp_path, rank=0)
    flight.trace_inflight_add(["r-2", "r-1"])
    snap = flight.snapshot()
    assert snap["inflight_traces"] == ["r-1", "r-2"]
    flight.trace_inflight_drop(["r-1", "ghost"])
    assert flight.inflight_traces() == ["r-2"]
    flight.flush()
    side = json.loads(
        (tmp_path / "heartbeat-rank0.json").read_text()
    )
    assert side["inflight_traces"] == ["r-2"]
    flight.reset()
    assert flight.inflight_traces() == []


def test_slo_decomposition_block_aggregates_and_validates():
    from rocm_mpi_tpu.serving import slo

    decomps = {
        "a": {"queue_wait": 0.1, "device": 0.4},
        "b": {"queue_wait": 0.3, "device": 0.2, "backoff": 0.05},
    }
    block = slo.decomposition_block(decomps, {"a": 0, "b": 1})
    assert block["n"] == 2
    assert block["stages"]["queue_wait"]["n"] == 2
    assert block["stages"]["queue_wait"]["mean"] \
        == pytest.approx(0.2)
    assert block["hops"] == {"max": 1, "rerouted": 1}
    assert slo.validate_decomposition_block(block) == []
    assert slo.validate_decomposition_block(None) == []
    assert slo.decomposition_block({}, {}) is None
    assert slo.validate_decomposition_block(
        {"n": 2, "stages": {"bogus": {"mean": 1, "p50": 1, "p99": 1}},
         "hops": {"max": 0, "rerouted": 0}}
    ) != []


def test_summarize_counts_tspans_and_traced_requests(tmp_path):
    events.configure(directory=tmp_path, rank=0)
    for i in range(3):
        tracing.emit_tspan("trace.submit", tracing.mint(f"s-{i}"))
    tracing.emit_tspan("trace.route", tracing.mint("s-0"))
    streams, _ = aggregate.load_rank_streams(tmp_path)
    summary = aggregate.summarize(streams)
    assert summary["tspans"] == {"trace.submit": 3, "trace.route": 1}
    assert summary["trace_requests"] == 3
