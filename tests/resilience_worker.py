"""Rank worker for the launcher's failure-path drills (run via
spawn_ranks; tests/test_resilience.py is the driver).

Each rank steps through `--steps` fault points (the instrumented-site
shape run_segmented uses), so an injected `kill@step=K,rank=R` spec —
forwarded by the launcher through RMT_INJECT_FAULT — kills exactly rank
R at exactly step K. Surviving ranks then block in `--hang-after` mode
(stand-in for a collective that can never complete once a peer is dead),
which is precisely the state the launcher's first-failure supervision
must detect and put down within the peer grace window — instead of every
survivor burning the full timeout.

jax-free on purpose: the drill measures LAUNCHER supervision semantics
(heartbeat, first-failure record, peer kill) deterministically and in
seconds; the gloo-real analog lives in the slow lane
(tests/test_resilience.py::test_kill_rank_mid_collective_gloo).
"""

import argparse
import sys
import time


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=8)
    p.add_argument("--step-s", type=float, default=0.05)
    p.add_argument(
        "--hang-after", action="store_true",
        help="after the step loop, block ~forever (the hung-collective "
        "stand-in the launcher must kill)",
    )
    args = p.parse_args()

    from rocm_mpi_tpu.parallel.distributed import process_id
    from rocm_mpi_tpu.resilience import faults

    rank = process_id()
    for step in range(1, args.steps + 1):
        faults.fault_point("segment", step=step)
        time.sleep(args.step_s)
    print(f"WORKER_DONE rank={rank}", flush=True)
    if args.hang_after:
        time.sleep(3600)
    return 0


if __name__ == "__main__":
    sys.exit(main())
