"""Tier-1 self-lint gate: the repo's own gate scope must be graftlint-clean,
fast, and the analyzer must provably catch the two measured historical bug
classes (the acceptance oracle for the whole subsystem):

* GL01 — deleting the PR-1 donation guard (`mgr.wait_until_finished()`)
  from utils/checkpoint.py's run_segmented re-creates the async-save/
  donated-buffer overlap that corrupted every mid-run checkpoint.
* GL02 — re-adding a `pk.<KNOB> = …` module-global write to bench.py
  re-creates the trace-time mutation the old kernel-form ladder shipped.

The repo-wide run prints the per-rule findings table so a regression
names the rule that fired, and is budgeted (<5 s target, hard-capped
well above to keep CI unflaky) — safe for `not slow` tier-1.
"""

from __future__ import annotations

import pathlib
import time

from rocm_mpi_tpu.analysis import (
    gate_exit_code,
    lint_paths,
    lint_source,
    rule_table,
)

REPO = pathlib.Path(__file__).resolve().parents[1]
GATE_SCOPE = [
    str(REPO / "rocm_mpi_tpu"),
    str(REPO / "apps"),
    str(REPO / "bench.py"),
]


def test_repo_is_lint_clean_and_fast():
    t0 = time.monotonic()
    findings, scanned = lint_paths(GATE_SCOPE)
    elapsed = time.monotonic() - t0
    print(f"\ngraftlint self-lint: {scanned} files in {elapsed:.2f}s")
    print(rule_table(findings))
    live = [f for f in findings if not f.suppressed]
    assert gate_exit_code(findings) == 0, (
        "graftlint gate scope is dirty:\n"
        + "\n".join(f"{f.location()}: {f.rule}: {f.message}" for f in live)
    )
    assert scanned >= 40, f"gate scope shrank to {scanned} files?"
    # <5 s is the design target; the hard cap leaves headroom for slow CI
    # boxes without letting an accidental O(n²) regress unnoticed forever.
    assert elapsed < 30.0, f"self-lint took {elapsed:.1f}s"


def test_second_walk_hits_the_ast_cache():
    lint_paths(GATE_SCOPE)  # warm (or already warm from the test above)
    t0 = time.monotonic()
    lint_paths(GATE_SCOPE)
    cached = time.monotonic() - t0
    assert cached < 2.0, f"cached repo walk took {cached:.2f}s"


# ---------------------------------------------------------------------------
# The two historical bug classes, provably caught
# ---------------------------------------------------------------------------


def test_gl01_catches_deleted_checkpoint_donation_guard():
    path = REPO / "rocm_mpi_tpu" / "utils" / "checkpoint.py"
    src = path.read_text()
    assert "mgr.wait_until_finished()" in src, (
        "the PR-1 donation guard moved — update this oracle"
    )
    mutated = "\n".join(
        line for line in src.splitlines()
        if "wait_until_finished" not in line
    )
    assert mutated != src
    before = [f for f in lint_source(src, str(path))
              if f.rule == "GL01" and not f.suppressed]
    after = [f for f in lint_source(mutated, str(path))
             if f.rule == "GL01" and not f.suppressed]
    assert before == [], "pristine checkpoint.py must be GL01-clean"
    assert after, (
        "deleting mgr.wait_until_finished() must re-create the measured "
        "async-save donation race and GL01 must catch it"
    )
    assert any("async save" in f.message for f in after)


def test_gl08_catches_deleted_uniformity_guard():
    """The interprocedural acceptance oracle: gather_to_host0's
    `process_count() == 1` early return is a UNIFORM branch (legal);
    rewriting it into a rank-dependent exit in front of the
    process_allgather re-creates the PR-6/PR-7 divergence class — one
    rank skips a host collective its peers enter — and GL08 must catch
    it."""
    path = REPO / "rocm_mpi_tpu" / "parallel" / "gather.py"
    src = path.read_text()
    assert "if jax.process_count() == 1:" in src, (
        "the gather uniformity guard moved — update this oracle"
    )
    mutated = src.replace(
        "if jax.process_count() == 1:",
        "if jax.process_index() != 0:",
    )
    before = [f for f in lint_source(src, str(path))
              if f.rule == "GL08" and not f.suppressed]
    after = [f for f in lint_source(mutated, str(path))
             if f.rule == "GL08" and not f.suppressed]
    assert before == [], "pristine gather.py must be GL08-clean"
    assert after, (
        "a rank-dependent early exit in front of process_allgather must "
        "re-create the collective-divergence hazard and GL08 must catch "
        "it"
    )
    assert any("rank-dependent" in f.message for f in after)


def test_interprocedural_pass_is_active_in_the_gate():
    """The zero-findings pin must cover the whole-program engine, not
    just the per-file rules: the gate scope linted WITHOUT the
    interprocedural pass must be missing the one accepted (suppressed)
    GL08 verdict the full pass produces — proof lint_paths actually ran
    the engine."""
    from rocm_mpi_tpu.analysis.core import lint_paths as _lint_paths

    full, _ = _lint_paths(GATE_SCOPE)
    per_file_only, _ = _lint_paths(GATE_SCOPE, interprocedural=False)
    gl08_full = [f for f in full if f.rule == "GL08"]
    gl08_flat = [f for f in per_file_only if f.rule == "GL08"]
    assert gl08_full and all(f.suppressed for f in gl08_full), (
        "the weak_scaling rung sit-out should be the one accepted GL08 "
        "verdict (suppressed with a why-comment)"
    )
    assert gl08_flat == [], (
        "per-file mode has no engine, so the cross-module verdict must "
        "vanish — if it fired here the interprocedural pin is vacuous"
    )


def test_gl02_catches_restored_bench_global_mutation():
    path = REPO / "bench.py"
    src = path.read_text()
    mutated = src + (
        "\n\nimport rocm_mpi_tpu.ops.pallas_kernels as pk\n"
        'pk.EQC_BODY_FORM = "conly"  # the pre-PR-1 ladder hazard\n'
    )
    before = [f for f in lint_source(src, "bench.py")
              if f.rule == "GL02" and not f.suppressed]
    after = [f for f in lint_source(mutated, "bench.py")
             if f.rule == "GL02" and not f.suppressed]
    assert before == [], "pristine bench.py must be GL02-clean"
    assert after and any("mutates module" in f.message for f in after)


def test_gate_scope_suppressions_all_live():
    """The --strict-suppressions pin: every disable directive in the
    gate scope still covers a finding.  A refactor that fixes (or
    moves) the suppressed code must delete its directive in the same
    change, or this test names the dead comment."""
    from rocm_mpi_tpu.analysis.core import audit_suppressions

    findings, _ = lint_paths(GATE_SCOPE)
    stale = audit_suppressions(GATE_SCOPE, findings)
    assert stale == [], "\n".join(
        f"{f.location()}: {f.message}" for f in stale
    )
    # …and the accepted verdicts those directives exist for are still
    # being produced (the audit is only meaningful against a lint run
    # that actually exercises the suppressions).
    suppressed = [f for f in findings if f.suppressed]
    assert len(suppressed) >= 6, (
        "the known accepted-verdict count shrank — if findings were "
        "fixed for real, their directives should have been deleted too"
    )


def test_fixture_dir_is_excluded_from_directory_walks():
    # The deliberately-buggy fixtures must never leak into a `tests/`-wide
    # lint invocation (e.g. someone running the CLI over the whole repo).
    findings, _ = lint_paths([str(REPO / "tests")])
    files = {f.file for f in findings}
    assert not any("analysis_fixtures" in f for f in files), files
