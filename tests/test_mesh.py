"""GlobalGrid topology/geometry tests (D1/D3/D9 parity)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from rocm_mpi_tpu.parallel import GlobalGrid, init_global_grid, suggest_dims


def test_suggest_dims_near_square():
    assert suggest_dims(8, 2) == (4, 2)
    assert suggest_dims(4, 2) == (2, 2)
    assert suggest_dims(1, 2) == (1, 1)
    assert suggest_dims(8, 3) == (2, 2, 2)
    assert suggest_dims(6, 2) == (3, 2)
    assert suggest_dims(7, 2) == (7, 1)
    assert suggest_dims(12, 3) == (3, 2, 2)


def test_init_topology_8_devices():
    grid = init_global_grid(256, 256)
    assert grid.dims == (4, 2)
    assert grid.nprocs == 8
    assert grid.local_shape == (64, 128)
    assert grid.axis_names == ("gx", "gy")
    # 2.0.0 cartesian coords cover the mesh
    coords = {grid.device_coords(d) for d in grid.mesh.devices.flat}
    assert coords == {(i, j) for i in range(4) for j in range(2)}


def test_trailing_unit_axis_dropped():
    # Reference idiom: init_global_grid(nx, ny, 1) for a 2D run
    # (diffusion_2D_ap.jl:17).
    grid = init_global_grid(128, 128, 1, dims=(2, 2))
    assert grid.ndim == 2
    assert grid.global_shape == (128, 128)


def test_geometry_matches_reference_formulas():
    # dx = lx/nx_g, cell center = x_g + dx/2 (diffusion_2D_ap.jl:19,28).
    grid = init_global_grid(128, 64, lengths=(10.0, 10.0), dims=(1, 1))
    dx, dy = grid.spacing
    assert dx == pytest.approx(10.0 / 128)
    assert dy == pytest.approx(10.0 / 64)
    x = grid.cell_centers(0)
    assert x.shape == (128,)
    assert float(x[0]) == pytest.approx(dx / 2)
    assert float(x[-1]) == pytest.approx(10.0 - dx / 2)


def test_local_cell_centers_tile_global():
    grid = init_global_grid(64, 64, dims=(4, 2))
    x_global = np.asarray(grid.cell_centers(0))
    tiles = [np.asarray(grid.local_cell_centers(0, i)) for i in range(4)]
    np.testing.assert_allclose(np.concatenate(tiles), x_global)


def test_sharding_places_shards():
    grid = init_global_grid(64, 64, dims=(4, 2))
    x = jax.device_put(jnp.zeros(grid.global_shape), grid.sharding)
    assert len(x.addressable_shards) == 8
    assert x.addressable_shards[0].data.shape == grid.local_shape


def test_indivisible_shape_raises():
    with pytest.raises(ValueError):
        GlobalGrid(
            mesh=init_global_grid(64, 64, dims=(4, 2)).mesh,
            global_shape=(63, 64),
            lengths=(10.0, 10.0),
        )


def test_explicit_dims_with_trailing_unit_axis():
    grid = init_global_grid(128, 128, 1, dims=(2, 2, 1))
    assert grid.ndim == 2
    assert grid.dims == (2, 2)


def test_warns_when_devices_dropped():
    with pytest.warns(UserWarning, match="using 4 of 8"):
        grid = init_global_grid(250, 250)
    assert grid.nprocs == 4
