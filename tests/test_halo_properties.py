"""Property-based halo-exchange tests (hypothesis): for arbitrary grid
shapes, mesh dims, and ghost widths, `exchange_halo` inside shard_map must
reproduce a trivially-correct numpy assembly of each shard's padded block
(neighbor values where the domain continues, zeros past the edge).

This generalizes the hand-picked cases in test_halo.py across the
configuration space — the closest thing a communication layer gets to a
race detector (SURVEY.md §5.2: the reference relies on manual discipline;
here the property is machine-checked).
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

hypothesis = pytest.importorskip(
    "hypothesis", reason="property tests need the hypothesis package"
)
from hypothesis import given, settings, strategies as st  # noqa: E402
from rocm_mpi_tpu.utils.compat import shard_map  # noqa: E402

from rocm_mpi_tpu.parallel import exchange_halo, init_global_grid  # noqa: E402


def numpy_padded_oracle(g: np.ndarray, dims, coords, width: int):
    """Shard (coords) of global array g, padded by `width` with true
    neighbor values, zeros beyond the domain."""
    local = tuple(n // d for n, d in zip(g.shape, dims))
    out = np.zeros(tuple(ln + 2 * width for ln in local), dtype=g.dtype)
    for idx in np.ndindex(*out.shape):
        gcoord = tuple(
            c * ln + i - width for c, ln, i in zip(coords, local, idx)
        )
        if all(0 <= gc < n for gc, n in zip(gcoord, g.shape)):
            out[idx] = g[gcoord]
    return out


@st.composite
def halo_cases(draw):
    ndim = draw(st.integers(1, 3))
    dims, shape = [], []
    budget = 8  # device budget (conftest provides 8)
    for _ in range(ndim):
        d = draw(st.sampled_from([1, 2, 4]))
        while d > 1 and d * int(np.prod(dims or [1])) > budget:
            d //= 2
        local = draw(st.integers(2, 5))  # always >= the max width below
        dims.append(d)
        shape.append(d * local)
    width = draw(st.integers(1, 2))
    return tuple(shape), tuple(dims), width


@given(halo_cases())
@settings(max_examples=int(os.environ.get("RMT_PROP_EXAMPLES", "25")), deadline=None)
def test_exchange_matches_numpy_oracle(case):
    shape, dims, width = case
    grid = init_global_grid(
        *shape, lengths=tuple(1.0 for _ in shape), dims=dims
    )
    g = np.arange(int(np.prod(shape)), dtype=np.float64).reshape(shape)
    x = jax.device_put(jnp.asarray(g), grid.sharding)

    @jax.jit
    def padded(x):
        return shard_map(
            lambda b: exchange_halo(b, grid, width=width),
            mesh=grid.mesh,
            in_specs=grid.spec,
            out_specs=grid.spec,
        )(x)

    out = np.asarray(padded(x))
    local_p = tuple(
        n // d + 2 * width for n, d in zip(shape, dims)
    )
    # out is the per-shard padded blocks re-tiled into one global array of
    # shape dims[i] * local_p[i]; slice each block back out and compare.
    for coords in np.ndindex(*dims):
        sl = tuple(
            slice(c * lp, (c + 1) * lp) for c, lp in zip(coords, local_p)
        )
        block = out[sl]
        expect = numpy_padded_oracle(g, dims, coords, width)
        np.testing.assert_array_equal(block, expect, err_msg=(
            f"shape={shape} dims={dims} width={width} coords={coords}"
        ))
