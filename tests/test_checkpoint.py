"""Checkpoint/resume (SURVEY.md §5.4 upgraded — utils/checkpoint.py):
segmented runs must be bitwise-identical to straight runs, a resumed run
must land exactly where the uninterrupted one does, and restores must
come back with the original shardings. Exercised on the sharded mesh
(orbax saves per-shard) and at the app layer via the --checkpoint/--resume
flags."""

import pathlib
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from rocm_mpi_tpu.models.swe import SWEConfig, ShallowWater
from rocm_mpi_tpu.utils import checkpoint as ckpt

ROOT = pathlib.Path(__file__).resolve().parent.parent


def _swe(dims=(2, 4)):
    cfg = SWEConfig(
        global_shape=(32, 32), lengths=(10.0, 10.0), nt=48, warmup=0,
        dtype="f64", dims=dims,
    )
    model = ShallowWater(cfg)
    h, us = model.init_state()
    Mus = model.face_masks()
    advance = model.advance_fn("perf")
    adv = lambda s, n: tuple(advance(s[0], s[1], Mus, n))
    return model, adv, (h, us)


def test_segmented_run_bitwise_equals_straight(tmp_path):
    _, adv, state = _swe()
    ref = adv((jnp.copy(state[0]), tuple(map(jnp.copy, state[1]))), 48)
    out = ckpt.run_segmented(adv, state, 48, tmp_path, every=16)
    np.testing.assert_array_equal(np.asarray(out[0]), np.asarray(ref[0]))
    for ou, ru in zip(out[1], ref[1]):
        np.testing.assert_array_equal(np.asarray(ou), np.asarray(ru))
    assert ckpt.latest_step(tmp_path) == 48


def test_crash_resume_lands_on_straight_run(tmp_path):
    model, adv, state = _swe()
    ref = adv((jnp.copy(state[0]), tuple(map(jnp.copy, state[1]))), 48)
    # "Crash" after 32 of 48 steps...
    ckpt.run_segmented(adv, state, 32, tmp_path, every=16)
    assert ckpt.latest_step(tmp_path) == 32
    # ...then resume from a FRESH process-state template (new model,
    # new initializer arrays), as the app's --resume path does.
    h2, us2 = model.init_state()
    restored = ckpt.restore_state(tmp_path, 32, (h2, us2))
    assert restored[0].sharding == h2.sharding
    out = ckpt.run_segmented(
        adv, restored, 48, tmp_path, every=16, start_step=32
    )
    np.testing.assert_array_equal(np.asarray(out[0]), np.asarray(ref[0]))


def test_interval_and_window_validation(tmp_path):
    _, adv, state = _swe(dims=(1, 1))
    with pytest.raises(ValueError, match="interval"):
        ckpt.run_segmented(adv, state, 8, tmp_path, every=0)
    with pytest.raises(ValueError, match="start_step"):
        ckpt.run_segmented(adv, state, 8, tmp_path, every=4, start_step=9)


def test_latest_step_empty_dir(tmp_path):
    assert ckpt.latest_step(tmp_path / "nonexistent") is None


def test_app_checkpoint_then_resume(tmp_path):
    """The app-layer contract: a run checkpointed at nt=24 then resumed to
    nt=48 must end on the same field as one straight 48-step run."""
    d = tmp_path / "ck"
    straight = tmp_path / "straight.npy"
    resumed = tmp_path / "resumed.npy"
    common = [
        sys.executable, "apps/swe_2d.py", "--cpu-devices", "4",
        "--nx", "24", "--ny", "24", "--warmup", "0",
    ]

    def run(extra):
        proc = subprocess.run(
            common + extra, capture_output=True, text=True, timeout=600,
            cwd=ROOT,
        )
        assert proc.returncode == 0, proc.stderr
        return proc.stdout

    run(["--nt", "48", "--save-field", str(straight)])
    run(["--nt", "24", "--checkpoint", str(d), "--ckpt-every", "12"])
    out = run(["--nt", "48", "--checkpoint", str(d), "--resume",
               "--save-field", str(resumed)])
    assert "restoring step 24" in out
    np.testing.assert_array_equal(np.load(resumed), np.load(straight))

@pytest.mark.slow
def test_deep_schedule_checkpoint_resume_app(tmp_path):
    """The deep schedule is checkpointable too (quantum = sweep depth k):
    a --deep run checkpointed at 24 then resumed to 48 must end on the
    same field as one straight --deep 48-step run; the save interval
    rounds up to a multiple of k."""
    d = tmp_path / "ck"
    straight = tmp_path / "straight.npy"
    resumed = tmp_path / "resumed.npy"
    common = [
        sys.executable, "apps/swe_2d.py", "--cpu-devices", "4",
        "--nx", "24", "--ny", "24", "--warmup", "0", "--deep", "8",
    ]

    def run(extra):
        proc = subprocess.run(
            common + extra, capture_output=True, text=True, timeout=600,
            cwd=ROOT,
        )
        assert proc.returncode == 0, proc.stderr
        return proc.stdout

    run(["--nt", "48", "--save-field", str(straight)])
    out = run(["--nt", "24", "--checkpoint", str(d), "--ckpt-every", "10"])
    assert "rounded to 16" in out  # 10 → next multiple of k=8
    out = run(["--nt", "48", "--checkpoint", str(d), "--resume",
               "--save-field", str(resumed)])
    assert "restoring step 24" in out
    np.testing.assert_array_equal(np.load(resumed), np.load(straight))


def test_resume_refuses_quantum_misaligned_checkpoint(tmp_path):
    """A checkpoint written by one schedule must not silently lose steps
    under another: resuming a step-12 checkpoint with --deep 9 (quantum
    9, window 24) exits 2 with the mismatch spelled out."""
    d = tmp_path / "ck"
    common = [
        sys.executable, "apps/swe_2d.py", "--cpu-devices", "2",
        "--nx", "24", "--ny", "24", "--warmup", "0",
    ]
    proc = subprocess.run(
        common + ["--nt", "12", "--checkpoint", str(d), "--ckpt-every", "6"],
        capture_output=True, text=True, timeout=600, cwd=ROOT,
    )
    assert proc.returncode == 0, proc.stderr
    proc = subprocess.run(
        common + ["--nt", "36", "--deep", "9", "--checkpoint", str(d),
                  "--resume"],
        capture_output=True, text=True, timeout=600, cwd=ROOT,
    )
    assert proc.returncode == 2, (proc.returncode, proc.stdout)
    assert "not a multiple of the schedule's step quantum" in proc.stdout
