"""Framework benchmark — prints ONE JSON line.

Headline metric (driver BASELINE.json): Gpts/s/chip for 2D heat diffusion at
252² per chip — the reference's acceptance-run geometry (4 ranks × 126²
inner = global 252², docs/Temp_4_252_252.png) measured with the reference's
warmup-excluded timing (wtime/(nt-warmup), diffusion_2D_perf.jl:48-56).

Path benchmarked: the VMEM-resident multi-step Pallas kernel — at 252² the
whole field lives on-chip, so the entire time loop runs inside one kernel
(rocm_mpi_tpu.ops.pallas_kernels.fused_multi_step). dtype f32 (the TPU-native
choice; Mosaic has no f64 — the reference's f64 was the GPU-native choice).

vs_baseline: the reference publishes no numbers (BASELINE.md). The divisor is
an *estimate* of the reference's fused-kernel rate on one MI50: peak HBM BW
1024 GB/s × ~70% achievable for a memory-bound stencil ≈ 717 GB/s T_eff,
A_eff = 24 B/point (3 f64 passes, perf.jl:55) → ≈ 29.9 Gpts/s/GPU.

`--suite` additionally measures the whole ladder (per-step perf/hide at
252², temporal-blocked and per-step paths at 12288², 3D) and prints a
human-readable table to stderr — the source of BASELINE.md's measured
numbers. The default single-line contract is unchanged.
"""

import json
import sys

REF_ESTIMATE_GPTS = 29.9  # estimated MI50 fused-kernel rate (see docstring)


def run_suite() -> None:
    import jax

    if jax.default_backend() != "tpu":
        print(
            "bench.py --suite requires a TPU backend (off-TPU the kernels "
            "run in the Pallas interpreter — hours per row); skipping",
            file=sys.stderr,
        )
        return

    from rocm_mpi_tpu.config import DiffusionConfig
    from rocm_mpi_tpu.models import HeatDiffusion

    def row(label, shape, runner, nt, warmup, **kw):
        cfg = DiffusionConfig(
            global_shape=shape,
            lengths=(10.0,) * len(shape),
            nt=nt,
            warmup=warmup,
            dtype="f32",
            dims=(1,) * len(shape),
        )
        model = HeatDiffusion(cfg)
        r = getattr(model, runner)(**kw)
        print(
            f"{label:34s} {r.wtime_it * 1e6:12.3f} us/step  "
            f"T_eff={r.t_eff:8.1f} GB/s  {r.gpts:8.3f} Gpts/s",
            file=sys.stderr,
        )

    row("252² VMEM-resident loop", (252, 252), "run_vmem_resident",
        32_768 + 1_048_576, 32_768)
    row("252² per-step perf (ppermute)", (252, 252), "run",
        220_000, 20_000, variant="perf")
    row("252² per-step hide (overlap)", (252, 252), "run",
        220_000, 20_000, variant="hide")
    row("252² deep-halo sweeps (k=16)", (252, 252), "run_deep",
        32_768 + 1_048_576, 32_768)
    row("12288² temporal-blocked (k=8)", (12288, 12288), "run_hbm_blocked",
        328, 8)
    row("12288² per-step perf", (12288, 12288), "run", 110, 10,
        variant="perf")
    row("128³ 3D temporal-blocked (k=8)", (128, 128, 128), "run_hbm_blocked",
        3_208, 8)


def main() -> int:
    from rocm_mpi_tpu.config import DiffusionConfig
    from rocm_mpi_tpu.models import HeatDiffusion

    import jax

    if "--suite" in sys.argv:
        run_suite()

    # Step counts are large multiples of the in-kernel chunk (256): the
    # fixed host→device dispatch latency of the one timed XLA call (~65 ms
    # measured through the tunneled-chip transport) must be amortized to
    # noise, or it — not the kernel — is what gets measured. At ~0.4 µs/step
    # the 4.19M timed steps take ~1.7 s, making the dispatch overhead <4%.
    # Off-TPU the kernel runs in the Pallas *interpreter* — millions of
    # steps would take days — so shrink to a smoke-test step count there.
    if jax.default_backend() == "tpu":
        warmup, timed = 32_768, 4_194_304
    else:
        warmup, timed = 32, 256
        print(
            "bench.py: no TPU backend — interpret-mode smoke run "
            f"({timed} steps); the reported rate is NOT the benchmark",
            file=sys.stderr,
        )
    cfg = DiffusionConfig(
        global_shape=(252, 252),
        lengths=(10.0, 10.0),
        nt=warmup + timed,
        warmup=warmup,
        dtype="f32",
        dims=(1, 1),
    )
    model = HeatDiffusion(cfg)
    # No separate warm-up run needed: run_vmem_resident's own warmup call
    # compiles the (single, chunk-shared) program before the timer starts.
    result = model.run_vmem_resident()
    gpts = result.gpts
    print(
        f"252²/chip f32: {result.nt - result.warmup} timed steps, "
        f"{result.wtime_it * 1e6:.3f} µs/step, T_eff={result.t_eff:.1f} GB/s "
        f"(VMEM-resident; HBM-equivalent figure)",
        file=sys.stderr,
    )
    print(
        json.dumps(
            {
                "metric": "Gpts/s/chip (2D diffusion, 252²/chip)",
                "value": round(gpts, 4),
                "unit": "Gpts/s",
                "vs_baseline": round(gpts / REF_ESTIMATE_GPTS, 4),
            }
        )
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
