"""Framework benchmark — prints ONE JSON line (always, even on failure).

Headline metric (driver BASELINE.json): Gpts/s/chip for 2D heat diffusion at
252² per chip — the reference's acceptance-run geometry (4 ranks × 126²
inner = global 252², docs/Temp_4_252_252.png) measured with the reference's
warmup-excluded timing (wtime/(nt-warmup), diffusion_2D_perf.jl:48-56).

Path benchmarked: the VMEM-resident multi-step Pallas kernel — at 252² the
whole field lives on-chip, so the entire time loop runs inside one kernel
(rocm_mpi_tpu.ops.pallas_kernels.fused_multi_step). dtype f32 (the TPU-native
choice; Mosaic has no f64 — the reference's f64 was the GPU-native choice).

vs_baseline: the reference publishes no numbers (BASELINE.md). The divisor is
an *estimate* of the reference's fused-kernel rate on one MI50: peak HBM BW
1024 GB/s × ~70% achievable for a memory-bound stencil ≈ 717 GB/s T_eff,
A_eff = 24 B/point (3 f64 passes, perf.jl:55) → ≈ 29.9 Gpts/s/GPU.

Robustness contract (the reference's analog is "run and check the output",
README.md:14-19 — the run must COMPLETE): the tunneled chip is transiently
unavailable, and backend init can either fail fast (UNAVAILABLE) or hang for
minutes. The parent process therefore runs the measurement in a CHILD
subprocess under a wall-clock budget (default 300 s, env BENCH_BUDGET_S):

  - child hangs        → killed at the deadline; any measurement lines it
                         already FLUSHED are harvested (see below), and it
                         is retried if time remains;
  - child crashes      → harvested + retried with exponential backoff
                         (fresh process, so no poisoned cached-backend
                         state carries over);
  - budget exhausted   → the contractual JSON line is STILL emitted: the
                         best harvested measurement; if NO accelerator
                         attempt ever flushed a line (a stalled tunnel
                         hangs backend init itself), a reserved 60 s runs
                         a forced-CPU fallback child whose labeled
                         interpret-mode smoke value is the record — 0.0
                         with an "error" field only if even that fails.

Emit-as-you-go (the round-3 lesson, VERDICT r3 #1 — one 224 s
compile+measure attempt died with the tunnel and scored 0.0): the child
emits a FLOOR measurement first — the chunk-16 VMEM loop, whose short
unroll compiles in seconds — then upgrades to the chunk-256 flagship,
then (r5) runs the kernel-form ladder — conly / eqc+pad256 /
conly+pad256, the pending A/B's candidates as trace-time switches in
ops.pallas_kernels — re-emitting only improvements and giving the long
window to the within-run winner, so the driver's recorded stderr tail IS
the kernel-form measurement record. The child's last stdout line is
always its best real number and a kill can only cost the *upgrade*,
never the round's number. The parent prints exactly ONE line: the best
across all child attempts (the stdout contract is the parent's).

Retries are cheap because every child shares a persistent XLA compilation
cache (.jax_cache/ at the repo root, overridable via
JAX_COMPILATION_CACHE_DIR) — `bench.py --prime-cache` (run by startup.sh
when an accelerator is reachable) pre-populates it so even a first attempt
skips the multi-ten-second Mosaic compiles.

`--suite` additionally measures the whole ladder (per-step perf/hide at
252², temporal-blocked and per-step paths at 12288², 3D) and prints a
human-readable table to stderr — the source of BASELINE.md's measured
numbers. It runs inline (manual/diagnostic use; no subprocess shielding).

`--compare r{n} r{m}` diffs two banked BENCH_r{NN}.json trajectory
records (baseline first) with the regress gate's tolerance semantics:
a per-key delta table plus dropped/new rungs, exit 1 on any regression
beyond tolerance — ROADMAP item 5's first-class before/after report.
"""

import dataclasses
import json
import os
import re
import subprocess
import sys
import time

REF_ESTIMATE_GPTS = 29.9  # estimated MI50 fused-kernel rate (see docstring)
DEFAULT_BUDGET_S = 300.0
METRIC = "Gpts/s/chip (2D diffusion, 252²/chip)"
# THE benchmark geometry — one constant shared by _bench_model and the
# ladder's pad-label planner, so the planned and measured programs
# cannot drift (the same no-drift rule as the cache primer).
BENCH_SHAPE = (252, 252)
BENCH_DTYPE = "float32"  # a spelling both DiffusionConfig and np.dtype take

# Child exit codes (anything else = unexpected crash, retried).
RC_OK = 0
RC_NO_TPU = 3  # backend came up but is not an accelerator


def emit(value: float, vs_baseline: float, error: str | None = None) -> None:
    """The one contractual stdout line."""
    line = {
        "metric": METRIC,
        "value": round(value, 4),
        "unit": "Gpts/s",
        "vs_baseline": round(vs_baseline, 4),
    }
    if error:
        line["error"] = error
    print(json.dumps(line))
    sys.stdout.flush()


# --------------------------------------------------------------------------
# Child: one attempt at the real measurement (may hang/crash; parent shields)
# --------------------------------------------------------------------------


def _accelerated() -> bool:
    """True when jax dispatches to an accelerator (tpu or the tunneled-chip
    'axon' platform), False on the CPU fallback."""
    import jax

    return jax.devices()[0].platform != "cpu"


def _apply_platform_override() -> None:
    """Honor JAX_PLATFORMS via jax.config (utils.backend has the why)."""
    from rocm_mpi_tpu.utils.backend import apply_platform_override

    apply_platform_override()


def _setup_compilation_cache() -> None:
    """Point every bench process at one persistent XLA compilation cache so
    a retry (or a driver run after `--prime-cache`) skips the Mosaic
    compiles that dominated round 3's killed attempt. Best-effort: an
    older jax without a knob, or a read-only disk, must not break the run.

    Accelerator-only: on the CPU fallback the cache saves nothing (the
    smoke run is interpret-bound) and XLA:CPU AOT cache entries carry
    compile-machine feature sets that can SIGILL on feature mismatch
    (observed warning in the CPU contract tests).
    """
    from rocm_mpi_tpu.utils.backend import enable_persistent_cache

    enable_persistent_cache()


def _fault_seconds(name: str) -> float:
    """Test-only fault injection (tests/test_bench.py): seconds from a
    BENCH_FAULT_* env var, 0.0 when unset/malformed."""
    raw = os.environ.get(name, "")
    try:
        return float(raw) if raw else 0.0
    except ValueError:
        return 0.0


def _maybe_hang_after_emit() -> None:
    """Fault injection: simulate the round-3 failure shape (a child that
    produced a measurement and then stalled forever on the transport)."""
    if os.environ.get("BENCH_FAULT_HANG_AFTER_EMIT"):
        time.sleep(1e6)


def _maybe_emit_fake_real_line() -> None:
    """Fault injection: emit a measurement line WITHOUT an error field, as
    an accelerated child's floor emit would — so the CPU contract tests can
    exercise the parent's best_line harvest branch (the actual round-3
    fix), not just the smoke-line fallback."""
    raw = _fault_seconds("BENCH_FAULT_EMIT_REAL_VALUE")
    if raw:
        emit(raw, raw / REF_ESTIMATE_GPTS)


def _bench_model(nt: int, warmup: int):
    """THE benchmark model (the BASELINE.json geometry): 252²/chip f32,
    unsharded. One builder shared by the measuring child and the cache
    primer — cache priming only pays off if the primed program is
    bit-identical to the bench program, so the config must not be able
    to drift between the two."""
    from rocm_mpi_tpu.config import DiffusionConfig
    from rocm_mpi_tpu.models import HeatDiffusion

    cfg = DiffusionConfig(
        global_shape=BENCH_SHAPE,
        lengths=(10.0, 10.0),
        nt=nt,
        warmup=warmup,
        dtype=BENCH_DTYPE,
        dims=(1, 1),
    )
    return HeatDiffusion(cfg)


def child_main(budget_s: float) -> int:
    deadline = time.monotonic() + budget_s
    delay = _fault_seconds("BENCH_FAULT_INIT_DELAY_S")
    if delay:
        time.sleep(delay)  # simulated slow backend init (test injection)
    import jax  # noqa: F401  (backend init may raise/hang — parent shields)

    _apply_platform_override()
    _setup_compilation_cache()
    model = _bench_model

    if not _accelerated():
        # Interpret-mode smoke run: proves the path executes, NOT a rate.
        print(
            "bench.py: no accelerator backend — interpret-mode smoke run; "
            "the reported rate is NOT the benchmark",
            file=sys.stderr,
        )
        _maybe_emit_fake_real_line()
        if os.environ.get("BENCH_FAULT_SKIP_SMOKE"):
            # Fault injection: stand in for the ~30 s interpret run so the
            # kill/harvest contract tests are fast and timing-independent.
            # (emit rounds to 4 decimals — keep the stand-in value above
            # that resolution so the contract tests can assert > 0.)
            emit(0.001, 0.0, error="no accelerator backend; smoke skipped "
                                   "by fault injection")
        else:
            r = model(32 + 256, 32).run_vmem_resident()
            emit(r.gpts, r.gpts / REF_ESTIMATE_GPTS,
                 error="no accelerator backend; interpret-mode smoke value")
        _maybe_hang_after_emit()
        return RC_NO_TPU

    best = 0.0
    # One compiled-program cache across every rung (models.diffusion
    # _run_single_shard keys it by the full trace identity): identical
    # configs at different step counts — the flagship calibration, a
    # re-measured rung, the long window riding the winner — reuse ONE
    # trace instead of re-tracing per call. Pinned by the compiles.total
    # assertion in tests/test_bench.py.
    programs: dict = {}

    def emit_if_better(r, label):
        nonlocal best
        if r.gpts > best:
            best = r.gpts
            emit(best, best / REF_ESTIMATE_GPTS)
        print(
            f"{label}: {r.wtime_it * 1e6:.3f} µs/step, "
            f"T_eff={r.t_eff:.1f} GB/s, {r.gpts:.2f} Gpts/s "
            f"(best so far {best:.2f})",
            file=sys.stderr,
        )

    # Stage 1 — THE FLOOR: chunk-16 VMEM loop. The 16-step unroll compiles
    # in seconds (Mosaic compile time scales with the unroll), so a real
    # accelerator number lands on stdout almost immediately; everything
    # after this line is upgrade, not risk.
    t0 = time.monotonic()
    r = model(4_096 + 262_144, 4_096).run_vmem_resident(
        chunk=16, program_cache=programs
    )
    print(
        f"floor (chunk=16) compile+run {time.monotonic() - t0:.1f} s",
        file=sys.stderr,
    )
    emit_if_better(r, "floor 252² chunk-16")
    _maybe_hang_after_emit()

    # Stage 2 — the flagship chunk-256 program, short calibration window.
    if deadline - time.monotonic() < 40.0:
        return RC_OK
    warmup = 32_768
    t0 = time.monotonic()
    r2 = model(warmup + 262_144, warmup).run_vmem_resident(
        program_cache=programs
    )
    print(
        f"flagship (chunk=256) compile+run {time.monotonic() - t0:.1f} s",
        file=sys.stderr,
    )
    emit_if_better(r2, "252² chunk-256 calibration")

    # Stage 2.5 — the kernel-form ladder, run where the driver runs
    # (VERDICT r4 next #2's A/B, landed in the one harness guaranteed a
    # chip run): each candidate re-traces the same VMEM-resident program
    # with a different trace-time body form / layout, passed as EXPLICIT
    # kwargs per rung (body_form/pad_pow2 — ADVICE r5 #1: a mutated
    # module global would be silently ignored by any cached/reused
    # compiled advance; a kwarg changes the trace). Per-form rates go to
    # stderr — the driver's recorded tail IS the measurement record —
    # and the long window below then rides the within-run winner.
    # Emit-as-you-go still guarantees the floor: a compile hang here can
    # only cost the upgrade.
    import rocm_mpi_tpu.ops.pallas_kernels as pk

    best_cfg, best_form_gpts = ("eqc", False), r2.gpts
    per_step = r2.wtime_it
    for form, pad in (("conly", False), ("eqc", True), ("conly", True)):
        if deadline - time.monotonic() < 60.0:
            print("bench.py: budget exhausted mid-ladder; "
                  f"best so far {best_cfg}", file=sys.stderr)
            break
        label = f"252² chunk-256 {form}{'+pad256' if pad else ''}"
        t0 = time.monotonic()
        rv = model(warmup + 262_144, warmup).run_vmem_resident(
            body_form=form, pad_pow2=pad, program_cache=programs
        )
        # The plan can refuse a requested pad (VMEM budget): then neither
        # this row nor — should the rung win — the long-window record may
        # carry a pad label for an unpadded program (ADVICE r5 #4). The
        # winner keeps the EFFECTIVE config, so the long window re-runs
        # and labels what was actually measured. plan_vmem_loop is the
        # pure planner — valid even when the compiled program came from
        # the cache, which the retired last_pad_applied() flag never was.
        eff_pad = pad and pk.plan_vmem_loop(
            BENCH_SHAPE, BENCH_DTYPE, warmup + 262_144,
            body_form=form, pad_pow2=pad,
        ).pad_applied is not False
        if pad and not eff_pad:
            label += " (pad skipped)"
        print(
            f"{label} compile+run {time.monotonic() - t0:.1f} s",
            file=sys.stderr,
        )
        emit_if_better(rv, label)
        if rv.gpts > best_form_gpts:
            best_cfg, best_form_gpts = (form, eff_pad), rv.gpts
            per_step = rv.wtime_it
    print(f"kernel-form ladder winner: {best_cfg[0]}"
          f"{'+pad256' if best_cfg[1] else ''} "
          f"({best_form_gpts:.2f} Gpts/s calibration)", file=sys.stderr)

    # Stage 3 — a long timed window at the winner's rate: amortizes the
    # ~65 ms tunnel dispatch RTT to <2% (≥ ~4 s window) within what's left
    # of the budget. Mid-window transport stalls only ever bias a window
    # DOWN, so keeping the best of the emitted windows is sound.
    remaining = deadline - time.monotonic()
    target_s = max(4.0, min(15.0, remaining * 0.4))
    hard_cap_s = max(1.0, remaining - 10.0)
    timed = int(min(target_s, hard_cap_s) / per_step)
    timed = min(timed, 33_554_432)
    timed -= timed % warmup  # keep both windows chunk-divisible
    if timed < warmup:
        print(
            "bench.py: budget too tight for the long window; the "
            "calibration-window rate stands",
            file=sys.stderr,
        )
        return RC_OK
    print(
        f"long window: {timed} steps (~{timed * per_step:.1f} s target, "
        f"{remaining:.0f} s budget left)",
        file=sys.stderr,
    )
    r3 = model(warmup + timed, warmup).run_vmem_resident(
        body_form=best_cfg[0], pad_pow2=best_cfg[1], program_cache=programs
    )
    win = f"{best_cfg[0]}{'+pad256' if best_cfg[1] else ''}"
    emit_if_better(r3, f"252² chunk-256 {win} x{timed}")
    return RC_OK


def prime_cache() -> int:
    """Compile the bench programs into the persistent cache (tiny windows;
    no timing). Run by startup.sh under a bounded timeout so a later
    driver `bench.py` run — even a first attempt on a cold process —
    skips the Mosaic compiles."""
    _apply_platform_override()
    _setup_compilation_cache()
    if not _accelerated():
        print(
            "bench.py --prime-cache: no accelerator backend; nothing to "
            "prime (compiled kernels are TPU-only)",
            file=sys.stderr,
        )
        return 0

    model = _bench_model
    for label, nt, wu, chunk, form, pad in (
        ("floor chunk-16", 32, 16, 16, "eqc", False),
        ("flagship chunk-256", 512, 256, None, "eqc", False),
        # The stage-2.5 kernel-form ladder's candidates: prime them all so
        # the driver-run ladder pays zero compiles. Explicit trace-time
        # kwargs — the same ones the ladder passes — so the primed
        # programs are bit-identical to the measured ones.
        ("flagship conly", 512, 256, None, "conly", False),
        ("flagship eqc+pad256", 512, 256, None, "eqc", True),
        ("flagship conly+pad256", 512, 256, None, "conly", True),
    ):
        t0 = time.monotonic()
        model(nt, wu).run_vmem_resident(
            chunk=chunk, body_form=form, pad_pow2=pad
        )
        print(
            f"primed {label} in {time.monotonic() - t0:.1f} s",
            file=sys.stderr,
        )
    return 0


# --------------------------------------------------------------------------
# Suite (manual/diagnostic; inline, no shielding)
# --------------------------------------------------------------------------


def _next_bench_record_path() -> str:
    """BENCH_r{n}.json at the repo root — the bench trajectory: one
    schema-valid flat-metrics file (telemetry regress format,
    docs/TELEMETRY.md) per completed suite run, numbered consecutively so
    `python -m rocm_mpi_tpu.telemetry regress BENCH_r02.json --baseline
    BENCH_r01.json` gates run N against run N-1."""
    root = os.path.dirname(os.path.abspath(__file__))
    n = 1
    while os.path.exists(os.path.join(root, f"BENCH_r{n:02d}.json")):
        n += 1
    return os.path.join(root, f"BENCH_r{n:02d}.json")


def _write_bench_record(rows: dict, rate_rows: dict | None = None,
                        extra_metrics: dict | None = None) -> None:
    """Bank the suite's rates as a flat metrics baseline (all rates:
    higher is better; `rate_rows` are the serving drain rungs in
    requests/s rather than Gpts/s; `extra_metrics` are fully-formed
    {"value", "direction"} rows for the non-rate rungs — the tracing
    overhead fraction gates direction "lower"). Atomic tmp+rename so a
    mid-write kill cannot leave a torn record that bricks the schema
    gate."""
    if not rows and not rate_rows and not extra_metrics:
        return
    path = _next_bench_record_path()
    metrics = {
        f"suite.{label}.gpts": {"value": round(v, 4),
                                "direction": "higher"}
        for label, v in rows.items()
    }
    for label, v in (rate_rows or {}).items():
        metrics[f"suite.{label}.req_s"] = {
            "value": round(v, 4), "direction": "higher",
        }
    metrics.update(extra_metrics or {})
    doc = {
        "metrics": metrics,
    }
    tmp = path + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(doc, fh, indent=1, sort_keys=True)
        fh.write("\n")
    os.replace(tmp, path)
    print(f"bench.py --suite: banked {len(metrics)} rows into {path}",
          file=sys.stderr)


def _run_serve_drain_rung(n_requests: int = 16, nt_base: int = 2_000,
                          shapes=((64, 64), (96, 96))) -> tuple:
    """The serving drain rung (ISSUE 15, docs/SERVING.md "The
    pipeline"): the SAME synthetic trace through three drain modes —
    serial (depth 1), double-buffered (depth 2), and continuous
    (depth 2, 4 step segments per batch with boundary lane swap,
    docs/SERVING.md "Continuous batching") — on warmed program caches,
    plus a tracing-off pipelined arm for the request-tracing overhead
    rung; returns ({label: aggregate requests/s}, extra metric rows),
    the rungs `_write_bench_record` banks. time.monotonic interval
    arithmetic by design (the per-batch device walls ride the serve.*
    telemetry spans)."""
    import time as _time

    from rocm_mpi_tpu.serving.queue import Request as _Request
    from rocm_mpi_tpu.serving.service import (
        ServeConfig as _ServeConfig,
        SimulationService as _SimulationService,
    )

    serve_rows: dict = {}

    def _drain_trace(tag):
        return [
            _Request(
                request_id=f"{tag}-{i:03d}", workload="diffusion",
                global_shape=shapes[i % len(shapes)], dtype="f32",
                nt=nt_base + (nt_base // 20) * (i % 4),
                ic_scale=1.0 + 0.01 * i,
            )
            for i in range(n_requests)
        ]

    for depth, mode, segments in (
        (1, "serial", 1), (2, "pipelined", 1), (2, "continuous", 4),
    ):
        svc = _SimulationService(config=_ServeConfig(
            max_width=4, pipeline_depth=depth, segments=segments,
        ))
        # Warm pass: every program class compiles here, so the
        # measured pass is the steady state the service actually runs.
        svc.run_trace(_drain_trace(f"warm{mode}"))
        trace = _drain_trace(f"meas{mode}")
        for r in trace:
            svc.queue.submit(r)
        t0 = _time.monotonic()
        rep = svc.run_trace([])
        wall = _time.monotonic() - t0
        rate = rep.served / wall if wall > 0 else 0.0
        pipe = svc.pipeline_stats()
        cont = rep.continuous
        print(
            f"{'serve drain ' + mode:34s} {rep.served:3d} req "
            f"in {wall:8.3f} s  {rate:8.2f} req/s  "
            f"bubble={pipe['bubble']:.2f}"
            + (f"  occ={cont['occupancy']:.2f} "
               f"swaps={cont['swaps_in']}" if cont else ""),
            file=sys.stderr,
        )
        serve_rows[f"serve drain {mode}"] = rate

    # The tracing-overhead rung (docs/TELEMETRY.md "Request tracing"):
    # the SAME warmed pipelined drain with request tracing disabled —
    # the on/off req/s delta is the observability tax. Banked as a
    # direction-"lower" fraction so a tracing hot path that grows is a
    # regression even while absolute req/s still looks healthy.
    svc = _SimulationService(config=_ServeConfig(
        max_width=4, pipeline_depth=2, trace_requests=False,
    ))
    svc.run_trace(_drain_trace("warmuntraced"))
    for r in _drain_trace("measuntraced"):
        svc.queue.submit(r)
    t0 = _time.monotonic()
    rep = svc.run_trace([])
    wall = _time.monotonic() - t0
    untraced = rep.served / wall if wall > 0 else 0.0
    serve_rows["serve drain untraced"] = untraced
    traced = serve_rows.get("serve drain pipelined", 0.0)
    overhead = max(0.0, 1.0 - traced / untraced) if untraced > 0 else 0.0
    print(
        f"{'serve drain untraced':34s} {rep.served:3d} req "
        f"in {wall:8.3f} s  {untraced:8.2f} req/s  "
        f"trace overhead={overhead:.4f}",
        file=sys.stderr,
    )
    extra = {"suite.serve.trace_overhead": {
        "value": round(overhead, 4), "direction": "lower",
    }}
    return serve_rows, extra


def run_suite() -> None:
    if not _accelerated():
        print(
            "bench.py --suite requires an accelerator backend (off-TPU the "
            "kernels run in the Pallas interpreter — hours per row); skipping",
            file=sys.stderr,
        )
        return

    from rocm_mpi_tpu.config import DiffusionConfig
    from rocm_mpi_tpu.models import HeatDiffusion

    suite_rows: dict = {}

    def report(label, r):
        print(
            f"{label:34s} {r.wtime_it * 1e6:12.3f} us/step  "
            f"T_eff={r.t_eff:8.1f} GB/s  {r.gpts:8.3f} Gpts/s",
            file=sys.stderr,
        )
        suite_rows[label] = r.gpts

    def row(label, shape, runner, nt, warmup, dtype="f32", **kw):
        cfg = DiffusionConfig(
            global_shape=shape,
            lengths=(10.0,) * len(shape),
            nt=nt,
            warmup=warmup,
            dtype=dtype,
            dims=(1,) * len(shape),
        )
        model = HeatDiffusion(cfg)
        report(label, getattr(model, runner)(**kw))

    # config="auto" on the VMEM-resident rows: the suite measures what a
    # tuned deployment would run — a cache hit steers the row to the
    # banked winner (bitwise-safe knobs only at this op), a miss falls
    # back to the hand defaults, and either way the tune.hits/tune.misses
    # gauges below record which happened so `telemetry regress` can gate
    # tuned-vs-default suites instead of comparing them silently.
    row("252² VMEM-resident loop", (252, 252), "run_vmem_resident",
        32_768 + 1_048_576, 32_768, config="auto")
    row("252² per-step perf (ppermute)", (252, 252), "run",
        220_000, 20_000, variant="perf")
    row("252² per-step hide (overlap)", (252, 252), "run",
        220_000, 20_000, variant="hide")
    row("252² deep-halo sweeps (k=32)", (252, 252), "run_deep",
        32_768 + 1_048_576, 32_768)
    row("12288² temporal-blocked (k=8)", (12288, 12288), "run_hbm_blocked",
        328, 8)
    row("12288² deep-halo sweeps (k=8)", (12288, 12288), "run_deep",
        168, 8)
    row("12288² per-step perf", (12288, 12288), "run", 110, 10,
        variant="perf")
    # Labeled precision-trade fast path (--dtype bf16): halves the memory
    # traffic. Per-step bf16 rounds the state to bf16 EVERY step (error
    # grows with run length — BASELINE.md's error-vs-steps curve); the
    # temporal-blocked row below is the usable form: bf16 storage traffic,
    # f32 in-kernel sweeps, one rounding per k steps (error flat at
    # quantization level). The user opts in explicitly either way.
    row("12288² per-step perf (bf16)", (12288, 12288), "run", 110, 10,
        dtype="bf16", variant="perf")
    row("12288² temporal-blocked (k=8, bf16)", (12288, 12288),
        "run_hbm_blocked", 328, 8, dtype="bf16")
    row("128³ 3D temporal-blocked (k=8)", (128, 128, 128), "run_hbm_blocked",
        3_208, 8)
    row("128³ 3D deep-halo sweeps (k=8)", (128, 128, 128), "run_deep",
        3_208, 8)
    row("128³ 3D per-step perf", (128, 128, 128), "run", 1_100, 100,
        variant="perf")

    # The other workloads through the same layers, one perf + one
    # VMEM-resident row each at the diffusion rows' step protocol (wave:
    # 4 passes/step; swe: 2·(ndim+1) passes/step — each RunResult's t_eff
    # carries its own accounting). One loop so a protocol tune cannot
    # drift between workloads.
    from rocm_mpi_tpu.models import (
        AcousticWave,
        ShallowWater,
        SWEConfig,
        WaveConfig,
    )

    for name, cfg_cls, model_cls in (
        ("wave", WaveConfig, AcousticWave),
        ("swe", SWEConfig, ShallowWater),
    ):
        mcfg = cfg_cls(
            global_shape=(252, 252), lengths=(10.0, 10.0), nt=220_000,
            warmup=20_000, dtype="f32", dims=(1, 1),
        )
        report(
            f"252² {name} per-step perf",
            model_cls(mcfg).run(variant="perf"),
        )
        mcfg_v = dataclasses.replace(
            mcfg, nt=32_768 + 1_048_576, warmup=32_768
        )
        report(
            f"252² {name} VMEM-resident loop",
            model_cls(mcfg_v).run_vmem_resident(config="auto"),
        )

    # The wire-mode ladder (ROADMAP item 5's f32-vs-bf16 delta, docs/
    # PERF.md "Wire precision"): the SAME sharded schedule per row, only
    # the on-wire halo precision varies — the pair the next healthy chip
    # window finally banks as a measured wire delta. Needs a real mesh
    # (one device has no exchange to shrink); the suite's single-chip
    # rows above are unaffected either way.
    import jax as _jax

    n_dev = len(_jax.devices())
    if n_dev >= 2:
        from rocm_mpi_tpu.parallel.mesh import suggest_dims

        wire_dims = suggest_dims(n_dev, 2)
        for wm in ("f32", "bf16"):
            wcfg = DiffusionConfig(
                global_shape=tuple(252 * d for d in wire_dims),
                lengths=(10.0,) * 2, nt=220_000, warmup=20_000,
                dtype="f32", dims=wire_dims, wire_mode=wm,
            )
            report(
                f"252²/dev shard wire={wm} ({n_dev}dev)",
                HeatDiffusion(wcfg).run(variant="perf"),
            )
    else:
        print(
            "bench.py --suite: single device — skipping the wire-mode "
            "ladder rows (no exchange to measure)",
            file=sys.stderr,
        )

    # The multi-tenant batching rung (ROADMAP item 1, docs/SERVING.md):
    # B=1 vs B=4 lanes of the flagship shape through the SAME batched
    # "shard" program class the serving layer compiles — the aggregate
    # Gpts/s pair IS the batching win (one program, B lanes of work).
    # RunResult's shape-prod accounting makes the B-lane rate aggregate
    # automatically; the per-lane jnp explicit-exchange path is the
    # serving layer's own rung, so the ratio is honest.
    import jax as _jax2
    import numpy as _np

    from rocm_mpi_tpu.models.diffusion import RunResult as _RunResult

    for B in (1, 4):
        bcfg = DiffusionConfig(
            global_shape=BENCH_SHAPE, lengths=(10.0, 10.0),
            nt=22_000, warmup=2_000, dtype="f32", dims=(1, 1),
        )
        bmodel = HeatDiffusion(bcfg)
        advance, bg = bmodel.batched_advance_fn(batch=B)
        T0, Cp = bmodel.init_state()
        T0n = _np.asarray(T0)
        Tb = _jax2.device_put(
            _np.stack([T0n * (1.0 + 0.01 * i) for i in range(B)]),
            bg.sharding,
        )
        Cpb = _jax2.device_put(_np.asarray(Cp), bg.aux_sharding)
        steps_full = _jax2.device_put(
            _np.full(B, bcfg.nt, _np.int32), bg.batch_sharding
        )

        from rocm_mpi_tpu.utils import metrics as _metrics

        timer = _metrics.Timer(
            label="step_window", phase="step",
            steps=bcfg.nt - bcfg.warmup, variant=f"batched{B}",
            workload="diffusion",
        )
        Tb = advance(Tb, Cpb, steps_full, bcfg.warmup)
        timer.tic(Tb)
        Tb = advance(Tb, Cpb, steps_full, bcfg.nt - bcfg.warmup)
        wtime = timer.toc(Tb)
        report(
            f"252² batched B={B} lanes (shard)",
            _RunResult(T=Tb, wtime=wtime, nt=bcfg.nt,
                       warmup=bcfg.warmup, config=bcfg),
        )

    serve_rows, trace_metrics = _run_serve_drain_rung()

    # Bank the autotuner's resolve outcomes (tune.hits / tune.misses run
    # gauges + the per-key tune.resolve annotations) before the record:
    # a suite steered by a warm cache and one running hand defaults are
    # different measurements and must say so in their telemetry.
    from rocm_mpi_tpu.tuning import resolve as tuning_resolve

    tuning_resolve.emit_gauges()

    # The trajectory record is written only when the whole ladder ran —
    # a partial (killed) suite prints its rows to stderr but does not
    # bank a record that under-represents the machine.
    _write_bench_record(suite_rows, serve_rows, trace_metrics)


# --------------------------------------------------------------------------
# Trajectory compare (ROADMAP item 5: first-class before/after numbers)
# --------------------------------------------------------------------------


def _resolve_record(spec: str) -> str:
    """A --compare operand to a record path: 'r3' / 'r03' / '3' name the
    repo-root BENCH_r{NN}.json trajectory records; anything carrying a
    path separator or a .json suffix is an explicit path (tests and
    archived `docs/telemetry_r*/` records live elsewhere)."""
    s = spec.strip()
    if os.sep in s or s.endswith(".json"):
        return s
    m = re.fullmatch(r"r?(\d+)", s)
    if not m:
        raise ValueError(
            f"--compare operand {spec!r}: expected rN or a .json path"
        )
    root = os.path.dirname(os.path.abspath(__file__))
    return os.path.join(root, f"BENCH_r{int(m.group(1)):02d}.json")


def compare_records(base_spec: str, cur_spec: str,
                    tolerance: float | None = None) -> int:
    """`bench.py --compare r{n} r{m}`: the per-key trajectory report
    between two banked suite records — baseline first, current second.
    Reuses the regress machinery (same directions, same tolerance
    semantics as the committed gate) and prints one row per shared
    metric plus the keys only one record carries, so a silently
    dropped or newly added rung is visible instead of vanishing from
    the diff. Exit 1 when any metric moved the wrong way by more than
    the tolerance; exit 2 when an input cannot be read or the records
    share no keys."""
    from rocm_mpi_tpu.telemetry import regress

    try:
        base_path = _resolve_record(base_spec)
        cur_path = _resolve_record(cur_spec)
    except ValueError as e:
        print(f"bench.py --compare: {e}", file=sys.stderr)
        return 2
    base = regress.load_json(base_path)
    cur = regress.load_json(cur_path)
    bad = [p for p, d in ((base_path, base), (cur_path, cur)) if d is None]
    if bad:
        for p in bad:
            print(f"bench.py --compare: cannot read {p}", file=sys.stderr)
        return 2
    tol = regress.DEFAULT_TOLERANCE if tolerance is None else tolerance
    deltas = regress.compare(cur, base, tolerance=tol)
    base_keys = regress.extract_metrics(base)
    cur_keys = regress.extract_metrics(cur)
    if not deltas:
        print(
            f"bench.py --compare: no shared metric keys between "
            f"{base_path} and {cur_path}",
            file=sys.stderr,
        )
        return 2

    width = max(len(d.name) for d in deltas)
    print(f"bench.py --compare: {os.path.basename(base_path)} -> "
          f"{os.path.basename(cur_path)} (tolerance {tol:.0%})")
    for d in deltas:
        verdict = "REGRESSED" if d.regressed else "ok"
        print(
            f"  {d.name:{width}s}  {d.baseline:12.4f} -> "
            f"{d.current:12.4f}  {d.change:+8.1%}  "
            f"[{d.direction} is better] {verdict}"
        )
    for name in sorted(set(base_keys) - set(cur_keys)):
        print(f"  {name:{width}s}  dropped (baseline-only rung)")
    for name in sorted(set(cur_keys) - set(base_keys)):
        print(f"  {name:{width}s}  new (no baseline)")
    bad_rows = regress.regressions(deltas)
    print(
        f"  {len(deltas)} compared, {len(bad_rows)} regressed, "
        f"{len(set(base_keys) - set(cur_keys))} dropped, "
        f"{len(set(cur_keys) - set(base_keys))} new"
    )
    return 1 if bad_rows else 0


# --------------------------------------------------------------------------
# Parent: budget, retries, guaranteed JSON
# --------------------------------------------------------------------------


def _env_budget() -> float:
    raw = os.environ.get("BENCH_BUDGET_S", "")
    try:
        return float(raw) if raw else DEFAULT_BUDGET_S
    except ValueError:
        print(
            f"bench.py: ignoring malformed BENCH_BUDGET_S={raw!r}; "
            f"using {DEFAULT_BUDGET_S:.0f}s",
            file=sys.stderr,
        )
        return DEFAULT_BUDGET_S


def _as_text(raw) -> str:
    if raw is None:
        return ""
    if isinstance(raw, bytes):
        return raw.decode(errors="replace")
    return raw


def _run_child(budget_s: float, timeout_s: float, env=None):
    """One child invocation (the only subprocess machinery — both the
    accelerator attempts and the CPU fallback go through here). Returns
    (rc, stdout, stderr); rc is None when the child was killed at the
    timeout, with whatever it flushed still captured."""
    cmd = [
        sys.executable, os.path.abspath(__file__),
        "--child", f"--budget={budget_s:.0f}",
    ]
    try:
        proc = subprocess.run(
            cmd,
            capture_output=True,
            text=True,
            timeout=timeout_s,
            cwd=os.path.dirname(os.path.abspath(__file__)),
            env=env,
        )
        return proc.returncode, proc.stdout, proc.stderr
    except subprocess.TimeoutExpired as e:
        # subprocess.run kills the child and re-raises with whatever
        # output it had flushed — harvestable like any other outcome.
        return None, _as_text(e.stdout), _as_text(e.stderr)


def parent_main() -> int:
    budget = _env_budget()
    deadline = time.monotonic() + budget
    attempt = 0
    backoff = 5.0
    last_err = "no attempt ran"
    smoke_line = None  # JSON from a no-accelerator child, kept as fallback
    best_line = None  # best REAL measurement harvested across all attempts
    best_val = 0.0
    no_tpu_runs = 0

    def harvest(stdout: str) -> None:
        """Record every flushed measurement line — a killed child's floor
        is a real number (emit-as-you-go; the whole point of the design)."""
        nonlocal smoke_line, best_line, best_val
        for ln in stdout.splitlines():
            ln = ln.strip()
            if not (ln.startswith("{") and ln.endswith("}")):
                continue
            try:
                obj = json.loads(ln)
            except ValueError:
                continue
            if "value" not in obj:
                continue
            if "error" in obj:
                smoke_line = ln
            elif obj["value"] > best_val:
                best_val, best_line = obj["value"], ln

    # Budget reserved for a forced-CPU fallback child: if every accelerator
    # attempt dies pre-emit (a stalled chip tunnel hangs backend init
    # itself), the round record should be the labeled interpret-mode smoke
    # value, not 0.0. Released once any measurement line is in hand, and
    # never allowed to displace the only accelerator attempt a small
    # budget can afford.
    cpu_reserve = 60.0

    while True:
        reserve = cpu_reserve if not (best_line or smoke_line) else 0.0
        remaining = deadline - time.monotonic()
        if remaining < 55.0:  # not enough for compile + a meaningful window
            break
        if no_tpu_runs >= 2:
            # Backend comes up CPU-only consistently: this machine simply
            # has no accelerator; more retries can't change that.
            break
        attempt += 1
        child_budget = remaining - 10.0 - reserve
        if child_budget < 45.0:
            # The reserve would displace the only attempt that fits: the
            # accelerator attempt outranks the fallback insurance.
            child_budget = remaining - 10.0
        rc, stdout, stderr = _run_child(child_budget, child_budget)
        if rc is None:
            last_err = (
                f"attempt {attempt}: killed after {child_budget:.0f}s "
                "(backend init hang or slow transport)"
            )
        sys.stderr.write(stderr[-4000:])
        harvest(stdout)
        if rc == RC_OK and best_line:
            break  # child ran to completion; best_line is the answer
        if rc == RC_NO_TPU:
            # Backend up but CPU-only: in the driver env this means the chip
            # tunnel isn't attached yet — worth retrying; keep the smoke
            # line as a last-resort honest fallback.
            no_tpu_runs += 1
            last_err = f"attempt {attempt}: no accelerator backend (cpu only)"
        elif rc is not None and rc != RC_OK:
            tail = stderr.strip().splitlines()[-1:] or ["<no stderr>"]
            last_err = f"attempt {attempt}: rc={rc}: {tail[0][-300:]}"
        elif rc == RC_OK:
            last_err = f"attempt {attempt}: rc=0 but no measurement line"
        if rc is None and best_line is None and smoke_line is None:
            # The backend hung before flushing ANYTHING despite a long
            # budget: a shorter retry cannot do better — hand what's left
            # to the CPU fallback instead.
            print(f"bench.py: {last_err}; giving up on the accelerator",
                  file=sys.stderr)
            break
        # A retry is cheap once the compilation cache is warm; but when a
        # real number is already in hand and the remaining budget can't
        # fit a meaningful upgrade attempt, stop and report it.
        if (
            no_tpu_runs >= 2
            or deadline - time.monotonic() < 55.0 + backoff
        ):
            print(f"bench.py: {last_err}; giving up", file=sys.stderr)
            break
        print(f"bench.py: {last_err}; retrying", file=sys.stderr)
        time.sleep(backoff)
        backoff *= 2

    if best_line is None and smoke_line is None:
        # Every accelerator attempt died before flushing a line: spend the
        # reserve on a forced-CPU child whose labeled smoke value honors
        # the contract. The child env pins the CPU backend (a stalled
        # tunnel cannot hang it) and drops the init-delay fault, which
        # models an ACCELERATOR backend hang.
        remaining = deadline - time.monotonic()
        if remaining > 35.0:
            print(
                "bench.py: no measurement from any accelerator attempt; "
                "running the forced-CPU fallback child",
                file=sys.stderr,
            )
            fb_env = {
                k: v for k, v in os.environ.items()
                if k != "BENCH_FAULT_INIT_DELAY_S"
            }
            fb_env["JAX_PLATFORMS"] = "cpu"
            _, stdout, stderr = _run_child(
                remaining - 5, max(remaining - 2, 5), env=fb_env
            )
            sys.stderr.write(stderr[-4000:])
            harvest(stdout)

    if best_line:
        print(best_line)
        sys.stdout.flush()
        return 0
    # Budget exhausted without a real measurement: still honor the contract.
    if smoke_line:
        print(smoke_line)
        sys.stdout.flush()
        return 0
    emit(0.0, 0.0, error=f"benchmark did not complete within {budget:.0f}s "
                         f"budget; last: {last_err}")
    return 0


def main() -> int:
    argv = sys.argv[1:]
    if "--child" in argv:
        budget = DEFAULT_BUDGET_S
        for a in argv:
            if a.startswith("--budget="):
                budget = float(a.split("=", 1)[1])
        return child_main(budget)
    if "--prime-cache" in argv:
        return prime_cache()
    if "--compare" in argv:
        # Trajectory report: no backend, no subprocess — pure file diff.
        i = argv.index("--compare")
        ops = [a for a in argv[i + 1:] if not a.startswith("-")][:2]
        tol = None
        for a in argv:
            if a.startswith("--tolerance="):
                try:
                    tol = float(a.split("=", 1)[1])
                except ValueError:
                    print(f"bench.py --compare: malformed {a!r}",
                          file=sys.stderr)
                    return 2
        if len(ops) != 2:
            print("usage: bench.py --compare rN rM [--tolerance=F] "
                  "(baseline first, current second)", file=sys.stderr)
            return 2
        return compare_records(ops[0], ops[1], tol)
    if "--suite" in argv:
        # Manual/diagnostic mode: no subprocess shielding; honor the
        # platform override BEFORE run_suite's first backend use, and keep
        # exit code 0 (the no-TPU child code is a parent-retry signal) —
        # UNLESS --require-accelerator asks for queue semantics: there a
        # CPU fallback must exit nonzero so the measurement queue records
        # an INCOMPLETE artifact and retries, instead of promoting an
        # empty skip log as the completed chip suite.
        _apply_platform_override()
        _setup_compilation_cache()
        if "--require-accelerator" in argv:
            from rocm_mpi_tpu.utils.backend import require_accelerator

            require_accelerator("bench.py --suite")
        run_suite()
        child_main(_env_budget())
        return 0
    # The contract is ONE JSON line no matter what — including parent bugs
    # or environment surprises outside the retry loop.
    try:
        return parent_main()
    except Exception as e:  # noqa: BLE001
        emit(0.0, 0.0, error=f"bench parent crashed: {type(e).__name__}: {e}")
        return 0


if __name__ == "__main__":
    sys.exit(main())
