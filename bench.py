"""Framework benchmark — prints ONE JSON line (always, even on failure).

Headline metric (driver BASELINE.json): Gpts/s/chip for 2D heat diffusion at
252² per chip — the reference's acceptance-run geometry (4 ranks × 126²
inner = global 252², docs/Temp_4_252_252.png) measured with the reference's
warmup-excluded timing (wtime/(nt-warmup), diffusion_2D_perf.jl:48-56).

Path benchmarked: the VMEM-resident multi-step Pallas kernel — at 252² the
whole field lives on-chip, so the entire time loop runs inside one kernel
(rocm_mpi_tpu.ops.pallas_kernels.fused_multi_step). dtype f32 (the TPU-native
choice; Mosaic has no f64 — the reference's f64 was the GPU-native choice).

vs_baseline: the reference publishes no numbers (BASELINE.md). The divisor is
an *estimate* of the reference's fused-kernel rate on one MI50: peak HBM BW
1024 GB/s × ~70% achievable for a memory-bound stencil ≈ 717 GB/s T_eff,
A_eff = 24 B/point (3 f64 passes, perf.jl:55) → ≈ 29.9 Gpts/s/GPU.

Robustness contract (the reference's analog is "run and check the output",
README.md:14-19 — the run must COMPLETE): the tunneled chip is transiently
unavailable, and backend init can either fail fast (UNAVAILABLE) or hang for
minutes. The parent process therefore runs the measurement in a CHILD
subprocess under a wall-clock budget (default 300 s, env BENCH_BUDGET_S):

  - child hangs        → killed at the deadline, retried if time remains;
  - child crashes      → retried with exponential backoff (fresh process, so
                         no poisoned cached-backend state carries over);
  - budget exhausted   → the contractual JSON line is STILL emitted, with
                         "value": 0.0 and an explicit "error" field, rc 0.

The child sizes the timed window adaptively from a short calibration run so
compile + measurement always fit the remaining budget (no unbounded
multi-million-step run on a slow transport), with a floor that keeps the
~65 ms tunnel dispatch round-trip amortized to <2% of the timed window.

`--suite` additionally measures the whole ladder (per-step perf/hide at
252², temporal-blocked and per-step paths at 12288², 3D) and prints a
human-readable table to stderr — the source of BASELINE.md's measured
numbers. It runs inline (manual/diagnostic use; no subprocess shielding).
"""

import dataclasses
import json
import os
import subprocess
import sys
import time

REF_ESTIMATE_GPTS = 29.9  # estimated MI50 fused-kernel rate (see docstring)
DEFAULT_BUDGET_S = 300.0
METRIC = "Gpts/s/chip (2D diffusion, 252²/chip)"

# Child exit codes (anything else = unexpected crash, retried).
RC_OK = 0
RC_NO_TPU = 3  # backend came up but is not an accelerator


def emit(value: float, vs_baseline: float, error: str | None = None) -> None:
    """The one contractual stdout line."""
    line = {
        "metric": METRIC,
        "value": round(value, 4),
        "unit": "Gpts/s",
        "vs_baseline": round(vs_baseline, 4),
    }
    if error:
        line["error"] = error
    print(json.dumps(line))
    sys.stdout.flush()


# --------------------------------------------------------------------------
# Child: one attempt at the real measurement (may hang/crash; parent shields)
# --------------------------------------------------------------------------


def _accelerated() -> bool:
    """True when jax dispatches to an accelerator (tpu or the tunneled-chip
    'axon' platform), False on the CPU fallback."""
    import jax

    return jax.devices()[0].platform != "cpu"


def _apply_platform_override() -> None:
    """Re-apply a JAX_PLATFORMS env override through jax.config.

    This image pre-imports jax at interpreter startup with the platform
    pinned, so the env var alone (e.g. cpu for local testing) is silently
    ignored unless re-applied before first backend use.
    """
    plat = os.environ.get("JAX_PLATFORMS")
    if plat:
        import jax

        try:
            jax.config.update("jax_platforms", plat)
        except (RuntimeError, ValueError):
            pass  # backend already initialized; keep whatever it picked


def child_main(budget_s: float) -> int:
    deadline = time.monotonic() + budget_s
    import jax  # noqa: F401  (backend init may raise/hang — parent shields)

    _apply_platform_override()

    from rocm_mpi_tpu.config import DiffusionConfig
    from rocm_mpi_tpu.models import HeatDiffusion

    on_accel = _accelerated()

    def model(nt, warmup):
        cfg = DiffusionConfig(
            global_shape=(252, 252),
            lengths=(10.0, 10.0),
            nt=nt,
            warmup=warmup,
            dtype="f32",
            dims=(1, 1),
        )
        return HeatDiffusion(cfg)

    if not on_accel:
        # Interpret-mode smoke run: proves the path executes, NOT a rate.
        print(
            "bench.py: no accelerator backend — interpret-mode smoke run; "
            "the reported rate is NOT the benchmark",
            file=sys.stderr,
        )
        r = model(32 + 256, 32).run_vmem_resident()
        emit(r.gpts, r.gpts / REF_ESTIMATE_GPTS,
             error="no accelerator backend; interpret-mode smoke value")
        return RC_NO_TPU

    # Calibration: compile (one program serves all step counts — the outer
    # trip count is dynamic) + a ~1M-step timed window to estimate the rate.
    warmup = 32_768
    calib_steps = 1_048_576
    t0 = time.monotonic()
    r = model(warmup + calib_steps, warmup).run_vmem_resident()
    per_step = r.wtime_it
    print(
        f"calibration: {calib_steps} steps, {per_step * 1e6:.3f} µs/step "
        f"(incl. dispatch), compile+run {time.monotonic() - t0:.1f} s",
        file=sys.stderr,
    )

    # Size the real timed window: target a duration that amortizes the
    # ~65 ms dispatch RTT (<2% ⇒ ≥ ~4 s) but fits the remaining budget —
    # the budget wins on a degraded transport (a short window is a noisier
    # number; a killed child is no number at all).
    remaining = deadline - time.monotonic()
    target_s = max(4.0, min(15.0, remaining * 0.4))
    hard_cap_s = max(1.0, remaining - 10.0)
    timed = int(min(target_s, hard_cap_s) / per_step)
    timed = min(timed, 33_554_432)
    timed -= timed % warmup  # keep both windows chunk-divisible
    if timed < warmup:
        # Too little budget left for a second window: report the
        # calibration measurement rather than nothing.
        print(
            "bench.py: budget too tight for a full timed window; "
            "reporting the calibration-window rate",
            file=sys.stderr,
        )
        emit(r.gpts, r.gpts / REF_ESTIMATE_GPTS)
        return RC_OK
    print(
        f"timed window: {timed} steps (~{timed * per_step:.1f} s target, "
        f"{remaining:.0f} s budget left)",
        file=sys.stderr,
    )
    result = model(warmup + timed, warmup).run_vmem_resident()
    print(
        f"252²/chip f32: {timed} timed steps, "
        f"{result.wtime_it * 1e6:.3f} µs/step, T_eff={result.t_eff:.1f} GB/s "
        f"(VMEM-resident; HBM-equivalent figure)",
        file=sys.stderr,
    )
    # Best of the two measured windows (standard best-of-N): both are real
    # timed rates of the same compiled program; the tunneled transport adds
    # occasional mid-window stalls that only ever bias a window DOWN.
    gpts = max(result.gpts, r.gpts)
    if gpts != result.gpts:
        print(
            f"reporting the calibration window ({r.gpts:.2f} Gpts/s, "
            f"{calib_steps} steps) over the slower main window "
            f"({result.gpts:.2f} Gpts/s, {timed} steps)",
            file=sys.stderr,
        )
    emit(gpts, gpts / REF_ESTIMATE_GPTS)
    return RC_OK


# --------------------------------------------------------------------------
# Suite (manual/diagnostic; inline, no shielding)
# --------------------------------------------------------------------------


def run_suite() -> None:
    if not _accelerated():
        print(
            "bench.py --suite requires an accelerator backend (off-TPU the "
            "kernels run in the Pallas interpreter — hours per row); skipping",
            file=sys.stderr,
        )
        return

    from rocm_mpi_tpu.config import DiffusionConfig
    from rocm_mpi_tpu.models import HeatDiffusion

    def report(label, r):
        print(
            f"{label:34s} {r.wtime_it * 1e6:12.3f} us/step  "
            f"T_eff={r.t_eff:8.1f} GB/s  {r.gpts:8.3f} Gpts/s",
            file=sys.stderr,
        )

    def row(label, shape, runner, nt, warmup, dtype="f32", **kw):
        cfg = DiffusionConfig(
            global_shape=shape,
            lengths=(10.0,) * len(shape),
            nt=nt,
            warmup=warmup,
            dtype=dtype,
            dims=(1,) * len(shape),
        )
        model = HeatDiffusion(cfg)
        report(label, getattr(model, runner)(**kw))

    row("252² VMEM-resident loop", (252, 252), "run_vmem_resident",
        32_768 + 1_048_576, 32_768)
    row("252² per-step perf (ppermute)", (252, 252), "run",
        220_000, 20_000, variant="perf")
    row("252² per-step hide (overlap)", (252, 252), "run",
        220_000, 20_000, variant="hide")
    row("252² deep-halo sweeps (k=32)", (252, 252), "run_deep",
        32_768 + 1_048_576, 32_768)
    row("12288² temporal-blocked (k=8)", (12288, 12288), "run_hbm_blocked",
        328, 8)
    row("12288² deep-halo sweeps (k=8)", (12288, 12288), "run_deep",
        168, 8)
    row("12288² per-step perf", (12288, 12288), "run", 110, 10,
        variant="perf")
    # Labeled precision-trade fast path (--dtype bf16): halves the memory
    # traffic of the per-step schedule; ~0.6 % rel. error after 4 steps vs
    # f32 (documented in BASELINE.md) — the user opts in explicitly.
    row("12288² per-step perf (bf16)", (12288, 12288), "run", 110, 10,
        dtype="bf16", variant="perf")
    row("128³ 3D temporal-blocked (k=8)", (128, 128, 128), "run_hbm_blocked",
        3_208, 8)
    row("128³ 3D per-step perf", (128, 128, 128), "run", 1_100, 100,
        variant="perf")

    # Second workload (models.wave): per-step leapfrog through the same
    # layers — 4 passes/step (read U, U_prev, C2; write U⁺).
    from rocm_mpi_tpu.models.wave import AcousticWave, WaveConfig

    wcfg = WaveConfig(
        global_shape=(252, 252), lengths=(10.0, 10.0), nt=220_000,
        warmup=20_000, dtype="f32", dims=(1, 1),
    )
    report("252² wave per-step perf", AcousticWave(wcfg).run(variant="perf"))
    wcfg_v = dataclasses.replace(wcfg, nt=32_768 + 1_048_576, warmup=32_768)
    report(
        "252² wave VMEM-resident loop",
        AcousticWave(wcfg_v).run_vmem_resident(),
    )


# --------------------------------------------------------------------------
# Parent: budget, retries, guaranteed JSON
# --------------------------------------------------------------------------


def _env_budget() -> float:
    raw = os.environ.get("BENCH_BUDGET_S", "")
    try:
        return float(raw) if raw else DEFAULT_BUDGET_S
    except ValueError:
        print(
            f"bench.py: ignoring malformed BENCH_BUDGET_S={raw!r}; "
            f"using {DEFAULT_BUDGET_S:.0f}s",
            file=sys.stderr,
        )
        return DEFAULT_BUDGET_S


def parent_main() -> int:
    budget = _env_budget()
    deadline = time.monotonic() + budget
    attempt = 0
    backoff = 5.0
    last_err = "no attempt ran"
    smoke_line = None  # JSON from a no-accelerator child, kept as fallback
    no_tpu_runs = 0

    while True:
        remaining = deadline - time.monotonic()
        if remaining < 45.0:  # not enough for compile + a meaningful window
            break
        if no_tpu_runs >= 2:
            # Backend comes up CPU-only consistently: this machine simply
            # has no accelerator; more retries can't change that.
            break
        attempt += 1
        child_budget = remaining - 10.0
        cmd = [
            sys.executable, os.path.abspath(__file__),
            "--child", f"--budget={child_budget:.0f}",
        ]
        try:
            proc = subprocess.run(
                cmd,
                capture_output=True,
                text=True,
                timeout=child_budget,
                cwd=os.path.dirname(os.path.abspath(__file__)),
            )
        except subprocess.TimeoutExpired as e:
            stderr_tail = (e.stderr or b"")
            if isinstance(stderr_tail, bytes):
                stderr_tail = stderr_tail.decode(errors="replace")
            sys.stderr.write(stderr_tail[-2000:])
            last_err = (
                f"attempt {attempt}: killed after {child_budget:.0f}s "
                "(backend init hang or slow transport)"
            )
            print(f"bench.py: {last_err}", file=sys.stderr)
            continue

        sys.stderr.write(proc.stderr[-4000:])
        json_line = None
        for ln in reversed(proc.stdout.splitlines()):
            ln = ln.strip()
            if ln.startswith("{") and ln.endswith("}"):
                json_line = ln
                break
        if proc.returncode == RC_OK and json_line:
            print(json_line)
            sys.stdout.flush()
            return 0
        if proc.returncode == RC_NO_TPU:
            # Backend up but CPU-only: in the driver env this means the chip
            # tunnel isn't attached yet — worth retrying; keep the smoke
            # line as a last-resort honest fallback.
            smoke_line = json_line or smoke_line
            no_tpu_runs += 1
            last_err = f"attempt {attempt}: no accelerator backend (cpu only)"
        else:
            tail = proc.stderr.strip().splitlines()[-1:] or ["<no stderr>"]
            last_err = f"attempt {attempt}: rc={proc.returncode}: {tail[0][-300:]}"
        # Only sleep/log when another attempt will actually happen.
        if no_tpu_runs >= 2 or deadline - time.monotonic() < 45.0 + backoff:
            print(f"bench.py: {last_err}; giving up", file=sys.stderr)
            break
        print(f"bench.py: {last_err}; retrying", file=sys.stderr)
        time.sleep(backoff)
        backoff *= 2

    # Budget exhausted without a real measurement: still honor the contract.
    if smoke_line:
        print(smoke_line)
        sys.stdout.flush()
        return 0
    emit(0.0, 0.0, error=f"benchmark did not complete within {budget:.0f}s "
                         f"budget; last: {last_err}")
    return 0


def main() -> int:
    argv = sys.argv[1:]
    if "--child" in argv:
        budget = DEFAULT_BUDGET_S
        for a in argv:
            if a.startswith("--budget="):
                budget = float(a.split("=", 1)[1])
        return child_main(budget)
    if "--suite" in argv:
        # Manual/diagnostic mode: no subprocess shielding; honor the
        # platform override BEFORE run_suite's first backend use, and keep
        # exit code 0 (the no-TPU child code is a parent-retry signal).
        _apply_platform_override()
        run_suite()
        child_main(_env_budget())
        return 0
    # The contract is ONE JSON line no matter what — including parent bugs
    # or environment surprises outside the retry loop.
    try:
        return parent_main()
    except Exception as e:  # noqa: BLE001
        emit(0.0, 0.0, error=f"bench parent crashed: {type(e).__name__}: {e}")
        return 0


if __name__ == "__main__":
    sys.exit(main())
