// Native host-staged halo-exchange engine for the rocm_mpi_tpu framework.
//
// Role in the stack: the performance-credible implementation of the
// host-staged transport fallback (the reference's IGG_ROCMAWARE_MPI=0 path,
// where halos are staged through host memory instead of handed device-direct
// to the interconnect — /root/reference/scripts/setenv.sh:15-18,
// README.md:25-35). The Python HostStagedStepper (parallel/halo.py) is the
// readable oracle; this library is its native engine: the same
// pack → stage → unpack → per-shard-update cycle, but multithreaded C++
// with one thread pool task per shard. Loaded via ctypes (no pybind11 in
// this image); see rocm_mpi_tpu/parallel/native_halo.py.
//
// Semantics (must stay bit-identical to HostStagedStepper.step):
//   * global row-major field T of `ndim` (2 or 3) axes, shard grid `dims`,
//     non-overlapping shards of shape global/dims;
//   * each shard assembles a width-1 padded block: core memcpy'd, face
//     ghosts copied from neighbor shards through host memory, missing
//     ghosts (domain edge) zero;
//   * fused 5/7-point update: out = T + dt*lam/Cp * laplacian;
//   * global-boundary cells are Dirichlet-fixed (never updated).

#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

namespace {

constexpr int kMaxDim = 3;

struct Geom {
  int ndim;
  int64_t shape[kMaxDim];   // global cells per axis
  int64_t dims[kMaxDim];    // shard grid
  int64_t local[kMaxDim];   // shape / dims
  int64_t stride[kMaxDim];  // row-major strides of the global array
  double inv_d2[kMaxDim];
  double lam, dt;
};

inline int64_t gidx(const Geom& g, const int64_t* c) {
  int64_t off = 0;
  for (int a = 0; a < g.ndim; ++a) off += c[a] * g.stride[a];
  return off;
}

// Update one shard (cartesian coords `sc`) of the global field.
void update_shard(const Geom& g, const double* T, const double* Cp,
                  double* out, const int64_t* sc) {
  // Padded block: local + 2 per axis, zero-initialized (edge ghosts).
  int64_t pshape[kMaxDim], pstride[kMaxDim];
  int64_t pelems = 1;
  for (int a = 0; a < g.ndim; ++a) pshape[a] = g.local[a] + 2;
  for (int a = g.ndim - 1; a >= 0; --a) {
    pstride[a] = (a == g.ndim - 1) ? 1 : pstride[a + 1] * pshape[a + 1];
  }
  for (int a = 0; a < g.ndim; ++a) pelems *= pshape[a];
  std::vector<double> block(pelems, 0.0);

  int64_t lo[kMaxDim];  // global origin of this shard
  for (int a = 0; a < g.ndim; ++a) lo[a] = sc[a] * g.local[a];

  // Stage row-wise: the last axis is stride-1 in both the global field and
  // the padded block, so every staged row is one contiguous memcpy. A cell
  // of the padded block at p (0..local+1) maps to global coord lo + p - 1.
  // Core rows copy their core columns plus the in-domain last-axis face
  // ghosts; face-ghost rows (exactly one non-last axis outside the core)
  // copy core columns only; edge/corner rows are never read by the
  // 5/7-point stencil and stay zero.
  const int last = g.ndim - 1;
  int64_t p[kMaxDim] = {0};
  auto stage = [&](auto&& self, int axis) -> void {
    if (axis == last) {
      int64_t gcoord[kMaxDim];
      int outside = 0;
      for (int a = 0; a < last; ++a) {
        gcoord[a] = lo[a] + p[a] - 1;
        if (gcoord[a] < 0 || gcoord[a] >= g.shape[a]) return;  // off-domain
        if (p[a] == 0 || p[a] == g.local[a] + 1) ++outside;
      }
      if (outside > 1) return;  // edge/corner row: not read, skip
      // Padded last-axis positions [first, stop) to stage for this row.
      int64_t first = 1, stop = g.local[last] + 1;
      if (outside == 0) {  // core row: include in-domain face ghosts
        if (lo[last] > 0) first = 0;
        if (lo[last] + g.local[last] < g.shape[last]) stop = g.local[last] + 2;
      }
      gcoord[last] = lo[last] + first - 1;
      int64_t poff = first;
      for (int a = 0; a < last; ++a) poff += p[a] * pstride[a];
      std::memcpy(&block[poff], &T[gidx(g, gcoord)],
                  static_cast<size_t>(stop - first) * sizeof(double));
      return;
    }
    if (axis >= kMaxDim) return;  // unreachable; bounds recursion depth
    for (p[axis] = 0; p[axis] < g.local[axis] + 2; ++p[axis]) {
      self(self, axis + 1);
    }
  };
  stage(stage, 0);

  // Per-shard fused update from the staged block.
  int64_t c[kMaxDim];
  auto update = [&](auto&& self, int axis) -> void {
    if (axis == g.ndim) {
      int64_t gcoord[kMaxDim], poff = 0;
      bool boundary = false;
      for (int a = 0; a < g.ndim; ++a) {
        gcoord[a] = lo[a] + c[a];
        poff += (c[a] + 1) * pstride[a];
        if (gcoord[a] == 0 || gcoord[a] == g.shape[a] - 1) boundary = true;
      }
      int64_t go = gidx(g, gcoord);
      if (boundary) {  // Dirichlet: global edge cells never change
        out[go] = T[go];
        return;
      }
      double lap = 0.0, center = block[poff];
      for (int a = 0; a < g.ndim; ++a) {
        lap += (block[poff + pstride[a]] - 2.0 * center +
                block[poff - pstride[a]]) *
               g.inv_d2[a];
      }
      out[go] = center + g.dt * g.lam / Cp[go] * lap;
      return;
    }
    if (axis >= kMaxDim) return;  // unreachable; bounds recursion depth
    for (c[axis] = 0; c[axis] < g.local[axis]; ++c[axis]) {
      self(self, axis + 1);
    }
  };
  update(update, 0);
}

}  // namespace

extern "C" {

// One host-staged diffusion step. Returns 0 on success, nonzero on invalid
// geometry. `threads` <= 0 means hardware concurrency.
int rmt_host_staged_step(const double* T, const double* Cp, double* out,
                         const int64_t* shape, const int64_t* dims, int ndim,
                         const double* inv_d2, double lam, double dt,
                         int threads) {
  if (ndim < 1 || ndim > kMaxDim) return 1;
  Geom g;
  g.ndim = ndim;
  g.lam = lam;
  g.dt = dt;
  int64_t nshards = 1;
  for (int a = 0; a < ndim; ++a) {
    if (shape[a] <= 0 || dims[a] <= 0 || shape[a] % dims[a] != 0) return 2;
    g.shape[a] = shape[a];
    g.dims[a] = dims[a];
    g.local[a] = shape[a] / dims[a];
    g.inv_d2[a] = inv_d2[a];
    nshards *= dims[a];
  }
  for (int a = ndim - 1; a >= 0; --a) {
    g.stride[a] = (a == ndim - 1) ? 1 : g.stride[a + 1] * g.shape[a + 1];
  }

  unsigned hw = std::thread::hardware_concurrency();
  int nthreads = threads > 0 ? threads : (hw ? static_cast<int>(hw) : 1);
  if (nthreads > nshards) nthreads = static_cast<int>(nshards);

  auto worker = [&](int64_t first, int64_t last) {
    for (int64_t s = first; s < last; ++s) {
      int64_t sc[kMaxDim], rem = s;
      for (int a = ndim - 1; a >= 0; --a) {
        sc[a] = rem % g.dims[a];
        rem /= g.dims[a];
      }
      update_shard(g, T, Cp, out, sc);
    }
  };

  if (nthreads <= 1) {
    worker(0, nshards);
  } else {
    std::vector<std::thread> pool;
    int64_t per = (nshards + nthreads - 1) / nthreads;
    for (int t = 0; t < nthreads; ++t) {
      int64_t first = t * per;
      int64_t last = first + per > nshards ? nshards : first + per;
      if (first >= last) break;
      pool.emplace_back(worker, first, last);
    }
    for (auto& th : pool) th.join();
  }
  return 0;
}

// Version/capability probe for the ctypes loader.
int rmt_abi_version() { return 1; }

}  // extern "C"
