"""TPU smoke tier (VERDICT r2 ask #2): compiled-Mosaic correctness.

The main `tests/` suite deliberately runs on a virtual 8-device CPU mesh
with interpret-mode Pallas — it can't see Mosaic (TPU compiler) bugs. This
tier compiles every kernel path on the real chip at tiny sizes and asserts
against the jnp oracle — the hardware analog of the reference's "run it and
check the output" acceptance step (/root/reference/README.md:14-19).

Run manually on TPU hardware:  python -m pytest tests_tpu/ -q
(the whole tier auto-skips without an accelerator backend; the log of a
real-chip run is committed as docs/tpu_test_log_r3.txt).

Unlike tests/conftest.py this file must NOT force a platform or x64 —
the point is the real backend, f32, compiled (not interpret) Pallas.
"""

import jax
import pytest

# Persistent XLA compilation cache: on the tunneled chip a first compile
# costs tens of seconds and the tunnel flaps, so a re-run of this tier must
# never re-pay compiles a killed run already did. The helper carries the
# accelerator-only guard (XLA:CPU AOT entries can SIGILL on feature
# mismatch) and stays best-effort on older jax.
from rocm_mpi_tpu.utils.backend import enable_persistent_cache

enable_persistent_cache()

# Resumable sub-groups (VERDICT r4 weak #1): the whole tier's Mosaic
# compiles can outrun a short tunnel window, so chip_watcher.sh runs the
# tier one ranked group at a time (`pytest tests_tpu/ -m gN`) and promotes
# each group's log independently — a window that fits only g1 still banks
# the scored-path evidence. Ranking: g1 = the bench/per-step kernel family
# (the scored path), g2 = production-dispatch + schedule machinery,
# g3 = the other two workloads, g4 = the bf16 precision-trade family.
_GROUPS = ("g1", "g2", "g3", "g4")


# g1 is the scored-path group the short-window guarantee depends on: its
# membership is an explicit allowlist of name keywords, NOT a silent
# fallback — a new test matching no keyword fails collection loudly
# instead of quietly inflating g1's compile time (ADVICE r5 #3).
_G1_KEYWORDS = (
    "backend_is_accelerated", "whole_block", "striped", "kp_three_kernel",
    "vmem_multi_step", "temporal_blocked", "multi_step_cm", "fused_step_cm",
    "masked_step",
)


def _group(name: str) -> str:
    # "_swe_" not "swe": the latter would capture every "sweep" test.
    if "wave" in name or "_swe_" in name:
        return "g3"
    if "bf16" in name:
        return "g4"
    if any(k in name for k in ("hide", "deep", "real_stripes",
                               "model_runners")):
        return "g2"
    if any(k in name for k in _G1_KEYWORDS):
        return "g1"
    raise ValueError(
        f"test {name!r} matches no chip-tier group keyword: add a keyword "
        "to the right _group rule (or _G1_KEYWORDS, if it really belongs "
        "in the scored-path group) — silent g1 growth is what this guard "
        "prevents"
    )


def pytest_configure(config):
    for g in _GROUPS:
        config.addinivalue_line(
            "markers", f"{g}: chip-tier resumable sub-group (see conftest)"
        )


def pytest_collection_modifyitems(config, items):
    import rocm_mpi_tpu.ops.pallas_kernels as pk

    if pk._interpret_default():
        # Matches the kernels' own dispatch: any backend where
        # interpret=None resolves to the interpreter (cpu, gpu, ...) has
        # nothing to smoke-test here.
        skip = pytest.mark.skip(
            reason="TPU smoke tier needs a TPU backend "
            "(kernels would run interpreted — not the point of this tier)"
        )
        for item in items:
            item.add_marker(skip)
    for item in items:
        item.add_marker(getattr(pytest.mark, _group(item.name)))
