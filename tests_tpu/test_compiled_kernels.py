"""Every Mosaic (compiled Pallas) path vs the jnp oracle, tiny shapes, f32.

Covers the kernel inventory the CPU suite can only interpret: whole-block,
striped (divisible + partial-stripe), kp 3-kernel, VMEM-resident multi-step,
temporal-blocked HBM sweep (2D + 3D), deep-halo local compute, the Cm
per-step family, the hide strip kernels, and the model-level runners.
Tolerances are f32-scale; the arithmetic is identical up to association so
agreement is ~1e-6 relative.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import rocm_mpi_tpu.ops.pallas_kernels as pk
from rocm_mpi_tpu.config import DiffusionConfig
from rocm_mpi_tpu.models import HeatDiffusion
from rocm_mpi_tpu.ops.diffusion import step_fused, step_fused_padded

RTOL, ATOL = 2e-5, 1e-6


def _rand(shape, seed=0):
    return jax.random.uniform(jax.random.PRNGKey(seed), shape, jnp.float32)


def _close(got, ref):
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(ref), rtol=RTOL, atol=ATOL
    )


def test_backend_is_accelerated():
    assert jax.devices()[0].platform != "cpu"
    # interpret=None must resolve to compiled on this backend — otherwise
    # this whole tier silently tests the interpreter again.
    assert not pk._interpret_default()


def test_whole_block_compiled():
    Tp = _rand((34, 30))
    Cp = 1.0 + _rand((32, 28), seed=1)
    args = (1.3, 1e-4, (0.1, 0.07))
    _close(pk.fused_step_padded(Tp, Cp, *args), step_fused_padded(Tp, Cp, *args))


def test_striped_compiled(monkeypatch):
    monkeypatch.setattr(pk, "_VMEM_BLOCK_BUDGET_BYTES", 1024)
    Tp = _rand((66, 50))
    Cp = 1.0 + _rand((64, 48), seed=1)
    args = (1.0, 2e-4, (0.1, 0.1))
    _close(pk.fused_step_padded(Tp, Cp, *args), step_fused_padded(Tp, Cp, *args))


def test_striped_partial_stripe_compiled(monkeypatch):
    # Row count not a multiple of the stripe height: ceil grid + partial
    # trailing blocks must behave on Mosaic as in interpret mode.
    monkeypatch.setattr(pk, "_VMEM_BLOCK_BUDGET_BYTES", 1024)
    Tp = _rand((69, 50))
    Cp = 1.0 + _rand((67, 48), seed=1)
    args = (1.0, 2e-4, (0.1, 0.1))
    _close(pk.fused_step_padded(Tp, Cp, *args), step_fused_padded(Tp, Cp, *args))


def test_kp_three_kernel_compiled():
    Tp = _rand((34, 30))
    Cp = 1.0 + _rand((32, 28), seed=1)
    args = (1.3, 1e-4, (0.1, 0.07))
    _close(pk.kp_step_padded(Tp, Cp, *args), step_fused_padded(Tp, Cp, *args))


@pytest.mark.parametrize("form", ["eqc", "conly"])
def test_vmem_multi_step_compiled(form, monkeypatch):
    # Both equal-spacing body forms — the production 'eqc' and the pending
    # kernel-form A/B's 'conly' candidate — under ONE setup/oracle, so
    # flipping the default after the measurement carries zero Mosaic risk
    # and the two forms can never drift to different test conditions. The
    # rim assertion pins the bitwise Dirichlet hold (Cm==0 outside the
    # interior ⇒ rim frozen) against any Mosaic reassociation, matching
    # the CPU analog in tests/test_pallas_kernels.py.
    monkeypatch.setattr(pk, "EQC_BODY_FORM", form)
    T = _rand((32, 32))
    Cp = jnp.full((32, 32), 1.5, jnp.float32)
    args = (1.0, 1e-5, (0.1, 0.1))
    ref = T
    for _ in range(32):
        ref = step_fused(ref, Cp, *args)
    got = pk.fused_multi_step(T, Cp, *args, n_steps=32, chunk=16)
    _close(got, ref)
    rim = np.ones((32, 32), bool)
    rim[1:-1, 1:-1] = False
    np.testing.assert_array_equal(np.asarray(got)[rim], np.asarray(T)[rim])


def test_vmem_multi_step_pow2_pad_compiled(monkeypatch):
    # The padded-layout opt-in (VMEM_PAD_POW2, the chip A/B's pad_* rows):
    # a non-pow2 field pads to aligned axes, runs the same unrolled loop,
    # and slices back — must agree with the jnp oracle compiled.
    monkeypatch.setattr(pk, "VMEM_PAD_POW2", True)
    T = _rand((20, 24))
    Cp = 1.0 + _rand((20, 24), seed=1)
    args = (1.0, 1e-5, (0.1, 0.1))
    ref = T
    for _ in range(16):
        ref = step_fused(ref, Cp, *args)
    got = pk.fused_multi_step(T, Cp, *args, n_steps=16, chunk=8)
    assert got.shape == T.shape
    _close(got, ref)


def test_vmem_multi_step_unequal_spacing_compiled():
    # chunk >= 4 with unequal spacing: the general per-axis A/c branch
    # (equal spacing above takes the single-c specialization instead).
    T = _rand((32, 32))
    Cp = 1.0 + _rand((32, 32), seed=1)
    args = (1.0, 1e-5, (0.1, 0.07))
    ref = T
    for _ in range(16):
        ref = step_fused(ref, Cp, *args)
    _close(pk.fused_multi_step(T, Cp, *args, n_steps=16, chunk=8), ref)


def test_temporal_blocked_compiled():
    T = _rand((48, 48))
    Cp = 1.0 + _rand((48, 48), seed=1)
    args = (1.0, 1e-4, (0.5, 0.5))
    ref = T
    for _ in range(16):
        ref = step_fused(ref, Cp, *args)
    _close(pk.fused_multi_step_hbm(T, Cp, *args, 16, block_steps=8), ref)


def test_temporal_blocked_3d_compiled():
    T = _rand((32, 16, 128))
    Cp = 1.0 + _rand((32, 16, 128), seed=2)
    args = (0.8, 5e-5, (0.3, 0.4, 0.5))
    ref = T
    for _ in range(8):
        ref = step_fused(ref, Cp, *args)
    _close(pk.fused_multi_step_hbm(T, Cp, *args, 8, block_steps=4), ref)


def test_multi_step_cm_compiled():
    T = _rand((32, 32))
    Cp = 1.0 + _rand((32, 32), seed=1)
    lam, dt, spacing = 1.0, 1e-4, (0.1, 0.1)
    Cm = pk.edge_masked_cm(T, Cp, lam, dt)
    ref = T
    for _ in range(4):
        ref = step_fused(ref, Cp, lam, dt, spacing)
    _close(pk.multi_step_cm(T, Cm, spacing, 4), ref)


def test_fused_step_cm_whole_compiled():
    T = _rand((32, 28))
    Cp = 1.0 + _rand((32, 28), seed=1)
    lam, dt, spacing = 1.3, 1e-4, (0.1, 0.07)
    Cm = pk.edge_masked_cm(T, Cp, lam, dt)
    Tp = jnp.pad(T, ((1, 1), (1, 1)))
    _close(pk.fused_step_cm(Tp, Cm, spacing), step_fused(T, Cp, lam, dt, spacing))


def test_fused_step_cm_striped_compiled(monkeypatch):
    monkeypatch.setattr(pk, "_VMEM_BLOCK_BUDGET_BYTES", 1024)
    T = _rand((61, 48))
    Cp = 1.0 + _rand((61, 48), seed=1)
    lam, dt, spacing = 1.0, 2e-4, (0.1, 0.1)
    Cm = pk.edge_masked_cm(T, Cp, lam, dt)
    Tp = jnp.pad(T, ((1, 1), (1, 1)))
    _close(pk.fused_step_cm(Tp, Cm, spacing), step_fused(T, Cp, lam, dt, spacing))


@pytest.mark.parametrize("shape", [(64, 48), (16, 10, 8)])
def test_masked_step_striped_compiled(shape, monkeypatch):
    monkeypatch.setattr(pk, "_VMEM_BLOCK_BUDGET_BYTES", 1024)
    T = _rand(shape)
    Cp = 1.0 + _rand(shape, seed=1)
    lam, dt = 1.0, 2e-4
    spacing = (0.1,) * len(shape)
    Cm = pk.edge_masked_cm(T, Cp, lam, dt)
    _close(pk.masked_step(T, Cm, spacing), step_fused(T, Cp, lam, dt, spacing))


def test_masked_step_real_stripes_compiled():
    # Real dispatch (no budget shrink): 1024² f32 = 4 MB > the 2 MB budget
    # → the ghost-block striped per-step kernel at its production stripe
    # height, compiled.
    T = _rand((1024, 1024))
    Cp = 1.0 + _rand((1024, 1024), seed=1)
    lam, dt, spacing = 1.0, 1e-4, (0.01, 0.01)
    Cm = pk.edge_masked_cm(T, Cp, lam, dt)
    _close(pk.masked_step(T, Cm, spacing), step_fused(T, Cp, lam, dt, spacing))


def test_masked_step_bf16_stripes_compiled():
    # The bf16 precision-trade path: g=8 ghost blocks on (16,128)-tiled
    # bf16 must still compile and agree with the f32 oracle to bf16
    # precision (~0.4 % single-step). dt must respect the CFL bound
    # (min(d²)·Cp/λ/4.1 ≈ 2.4e-5 here): an unstable step amplifies the
    # bf16 rounding of the Laplacian beyond any fixed tolerance.
    T32 = _rand((2048, 2048))
    Cp = 1.0 + _rand((2048, 2048), seed=1)
    lam, dt, spacing = 1.0, 1e-5, (0.01, 0.01)
    Cm32 = pk.edge_masked_cm(T32, Cp, lam, dt)
    got = pk.masked_step(
        T32.astype(jnp.bfloat16), Cm32.astype(jnp.bfloat16), spacing
    )
    ref = step_fused(T32, Cp, lam, dt, spacing)
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(ref), rtol=2e-2, atol=1e-2
    )


def test_hide_strip_kernels_compiled():
    # The hide variant's production strip combination — fused_step_cm per
    # region with mask_boundary=False (models.diffusion._make_hide_step's
    # compiled-dtype sharded branch) — under shard_map on a 1-device mesh:
    # compiles the Cm strip kernels on the slab shapes even though
    # multi-chip hardware isn't available here.
    from jax import shard_map

    from rocm_mpi_tpu.parallel.mesh import init_global_grid
    from rocm_mpi_tpu.parallel.overlap import make_overlap_step

    grid = init_global_grid(48, 48, dims=(1, 1), devices=jax.devices()[:1])
    pu = lambda tp, cm, lam, dt, spacing: pk.fused_step_cm(tp, cm, spacing)
    local = make_overlap_step(grid, pu, (8, 8), mask_boundary=False)
    lam, dt, spacing = 1.0, 1e-4, grid.spacing
    T = _rand((48, 48))
    Cp = 1.0 + _rand((48, 48), seed=1)
    Cm = pk.edge_masked_cm(T, Cp, lam, dt)

    @jax.jit
    def step(T, Cm):
        return shard_map(
            lambda Tl, Cml: local(Tl, Cml, lam, dt, spacing),
            mesh=grid.mesh,
            in_specs=(grid.spec, grid.spec),
            out_specs=grid.spec,
            check_vma=False,
        )(T, Cm)

    _close(step(T, Cm), step_fused(T, Cp, lam, dt, spacing))


def test_hide_strip_kernels_narrow_slabs_compiled():
    # b_width=1 boundary slabs: 1-row/1-column region blocks are the
    # nastiest shapes Mosaic sees from the overlap ladder.
    from jax import shard_map

    from rocm_mpi_tpu.parallel.mesh import init_global_grid
    from rocm_mpi_tpu.parallel.overlap import make_overlap_step

    grid = init_global_grid(32, 32, dims=(1, 1), devices=jax.devices()[:1])
    pu = lambda tp, cm, lam, dt, spacing: pk.fused_step_cm(tp, cm, spacing)
    local = make_overlap_step(grid, pu, (1, 1), mask_boundary=False)
    lam, dt, spacing = 1.0, 1e-4, grid.spacing
    T = _rand((32, 32))
    Cp = 1.0 + _rand((32, 32), seed=1)
    Cm = pk.edge_masked_cm(T, Cp, lam, dt)

    @jax.jit
    def step(T, Cm):
        return shard_map(
            lambda Tl, Cml: local(Tl, Cml, lam, dt, spacing),
            mesh=grid.mesh,
            in_specs=(grid.spec, grid.spec),
            out_specs=grid.spec,
            check_vma=False,
        )(T, Cm)

    _close(step(T, Cm), step_fused(T, Cp, lam, dt, spacing))


def test_deep_halo_sweep_compiled():
    from rocm_mpi_tpu.parallel.deep_halo import make_deep_sweep
    from rocm_mpi_tpu.parallel.mesh import init_global_grid

    grid = init_global_grid(64, 64, dims=(1, 1), devices=jax.devices()[:1])
    lam, dt = 1.0, jnp.float32(1e-4)
    sched = make_deep_sweep(grid, 4, lam, dt, grid.spacing)
    T = _rand((64, 64))
    Cp = 1.0 + _rand((64, 64), seed=1)
    Cm = jax.jit(sched.prepare)(Cp)  # the once-per-advance Cp exchange
    ref = T
    for _ in range(4):
        ref = step_fused(ref, Cp, lam, dt, grid.spacing)
    _close(jax.jit(sched.sweep)(T, Cm), ref)


def test_deep_halo_hbm_shard_compiled():
    # Real dispatch: a 736² f32 shard pads to 752² = 2.26 MB > the VMEM
    # budget → the deep sweep's local compute is the temporal-blocked HBM
    # sweep (multi_step_cm_hbm), compiled.
    from rocm_mpi_tpu.parallel.deep_halo import make_deep_sweep
    from rocm_mpi_tpu.parallel.mesh import init_global_grid

    grid = init_global_grid(736, 736, dims=(1, 1), devices=jax.devices()[:1])
    lam, dt = 1.0, jnp.float32(1e-5)
    sched = make_deep_sweep(grid, 8, lam, dt, grid.spacing)
    T = _rand((736, 736))
    Cp = 1.0 + _rand((736, 736), seed=1)
    Cm = jax.jit(sched.prepare)(Cp)
    ref = T
    for _ in range(8):
        ref = step_fused(ref, Cp, lam, dt, grid.spacing)
    _close(jax.jit(sched.sweep)(T, Cm), ref)


def test_wave_kernel_compiled():
    # Second workload's Pallas kernel (ops.wave_kernels) vs its jnp twin.
    from rocm_mpi_tpu.ops.wave_kernels import (
        wave_step_padded,
        wave_step_padded_pallas,
    )

    Up = _rand((34, 30))
    Uprev = _rand((32, 28), seed=1)
    C2 = 1.0 + _rand((32, 28), seed=2)
    dt, spacing = 1e-3, (0.1, 0.07)
    _close(
        wave_step_padded_pallas(Up, Uprev, C2, dt, spacing),
        wave_step_padded(Up, Uprev, C2, dt, spacing),
    )


def test_wave_vmem_multi_step_compiled():
    # The whole-loop-in-VMEM leapfrog, compiled, vs the jnp per-step form.
    from rocm_mpi_tpu.models.wave import wave_step_fused
    from rocm_mpi_tpu.ops.wave_kernels import wave_multi_step

    U0 = _rand((32, 32))
    C2 = 1.0 + _rand((32, 32), seed=1)
    dt, spacing = 2e-3, (0.1, 0.1)
    ref, ref_prev = U0, jnp.copy(U0)
    for _ in range(16):
        ref, ref_prev = wave_step_fused(ref, ref_prev, C2, dt, spacing), ref
    got, got_prev = wave_multi_step(
        U0, jnp.copy(U0), C2, dt, spacing, 16, chunk=8
    )
    _close(got, ref)
    _close(got_prev, ref_prev)


def test_wave_deep_sweep_compiled():
    # The wave deep-halo sweep's masked VMEM kernel on a 1-device mesh.
    from rocm_mpi_tpu.models.wave import AcousticWave, WaveConfig
    from rocm_mpi_tpu.parallel.deep_halo import make_wave_deep_sweep

    cfg = WaveConfig(
        global_shape=(64, 64), lengths=(10.0, 10.0), nt=8, warmup=0,
        dtype="f32", dims=(1, 1),
    )
    model = AcousticWave(cfg, devices=jax.devices()[:1])
    U, Uprev, C2 = model.init_state()
    ref, _ = model.advance_fn("ap")(jnp.copy(U), jnp.copy(Uprev), C2, 8)
    sched = make_wave_deep_sweep(
        model.grid, 4, cfg.jax_dtype(cfg.dt), cfg.spacing
    )
    P = jax.jit(sched.prepare)(C2)
    sweep = jax.jit(sched.sweep)
    got, _ = sweep(*sweep(U, Uprev, P), P)
    _close(got, ref)


def test_temporal_blocked_k16_geometry_compiled():
    # r4: the deeper (g=16, tm=32) sweep geometry — 64-row slabs, 16
    # unrolled steps — must compile on Mosaic at narrow widths (wide rows
    # are envelope-gated; the width boundary itself is measured by
    # scripts/bench_tb_stripes.py, not asserted here).
    T32 = _rand((64, 48))
    Cp32 = 1.0 + _rand((64, 48), seed=1)
    lam, dt, spacing = 1.0, 1e-4, (0.1, 0.1)
    ref = T32
    for _ in range(16):
        ref = step_fused(ref, Cp32, lam, dt, spacing)
    got = pk.fused_multi_step_hbm(
        T32, Cp32, lam, dt, spacing, 16, block_steps=16
    )
    _close(got, ref)


def test_bf16_storage_only_multi_step_compiled():
    # r4: bf16 operands upcast to f32 inside the kernel and round back
    # once per chunk (storage-only bf16). New Mosaic surface: the
    # convert_element_type pair inside the unrolled VMEM loop must
    # compile, and the result must track the f32 trajectory to bf16
    # resolution instead of freezing (the per-step-rounding failure mode
    # documented in docs/bf16_error_cpu252_perstep_r4.txt).
    T32 = _rand((64, 64))
    Cp32 = 1.0 + _rand((64, 64), seed=1)
    lam, dt, spacing = 1.0, 1e-4, (0.1, 0.1)
    ref = pk.fused_multi_step(T32, Cp32, lam, dt, spacing, 64, chunk=16)
    got16 = pk.fused_multi_step(
        T32.astype(jnp.bfloat16), Cp32.astype(jnp.bfloat16),
        lam, dt, spacing, 64, chunk=16,
    )
    assert got16.dtype == jnp.bfloat16  # rounds back to storage dtype
    np.testing.assert_allclose(
        np.asarray(got16, np.float32), np.asarray(ref), rtol=0.02,
        atol=0.02,
    )


def test_bf16_storage_only_tb_sweep_compiled():
    # The temporal-blocked edition: bf16 slabs, f32 sweep arithmetic,
    # one rounding per k-step sweep (the suite's bf16 tb row).
    T32 = _rand((64, 48))
    Cp32 = 1.0 + _rand((64, 48), seed=1)
    lam, dt, spacing = 1.0, 1e-4, (0.1, 0.1)
    ref = pk.fused_multi_step_hbm(
        T32, Cp32, lam, dt, spacing, 32, block_steps=8
    )
    got16 = pk.fused_multi_step_hbm(
        T32.astype(jnp.bfloat16), Cp32.astype(jnp.bfloat16),
        lam, dt, spacing, 32, block_steps=8,
    )
    assert got16.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(got16, np.float32), np.asarray(ref), rtol=0.02,
        atol=0.02,
    )


def test_wave_hide_strip_kernels_compiled():
    # The wave hide variant's production strip combination (r4): the
    # 3-operand leapfrog Pallas kernel per region with (U_prev, C2) as
    # core-only aux pytree — under shard_map on a 1-device mesh, so the
    # slab-shaped wave kernels compile on the chip even though the
    # sharded hide path needs >= 2 devices to be selected organically.
    from jax import shard_map

    from rocm_mpi_tpu.models.wave import wave_step_fused
    from rocm_mpi_tpu.ops.wave_kernels import wave_step_padded_pallas
    from rocm_mpi_tpu.parallel.mesh import init_global_grid
    from rocm_mpi_tpu.parallel.overlap import make_overlap_step

    grid = init_global_grid(48, 48, dims=(1, 1), devices=jax.devices()[:1])
    dt, spacing = 1e-3, grid.spacing

    def pu(tp, aux, lam, dt_, sp):
        del lam
        return wave_step_padded_pallas(tp, aux[0], aux[1], dt_, sp)

    local = make_overlap_step(grid, pu, (8, 8))
    U = _rand((48, 48))
    Uprev = _rand((48, 48), seed=1)
    C2 = 1.0 + _rand((48, 48), seed=2)

    @jax.jit
    def step(U, Uprev, C2):
        return shard_map(
            lambda Ul, Upl, C2l: local(Ul, (Upl, C2l), None, dt, spacing),
            mesh=grid.mesh,
            in_specs=(grid.spec,) * 3,
            out_specs=grid.spec,
            check_vma=False,
        )(U, Uprev, C2)

    _close(step(U, Uprev, C2), wave_step_fused(U, Uprev, C2, dt, spacing))


def test_model_runners_compiled():
    # The model-level fast paths end-to-end on the chip at tiny sizes.
    cfg = DiffusionConfig(
        global_shape=(64, 64), lengths=(10.0, 10.0), nt=32, warmup=8,
        dtype="f32", dims=(1, 1),
    )
    model = HeatDiffusion(cfg)
    r_perf = model.run(variant="perf")
    r_hide = model.run(variant="hide")
    r_vmem = model.run_vmem_resident()
    r_deep = model.run_deep(block_steps=8)
    r_tb = model.run_hbm_blocked(block_steps=8)
    np.testing.assert_array_equal(np.asarray(r_hide.T), np.asarray(r_perf.T))
    for r in (r_vmem, r_deep, r_tb):
        _close(r.T, r_perf.T)


def test_swe_padded_kernel_compiled():
    # Third workload (r4): the coupled padded SWE kernel vs its jnp twin.
    from rocm_mpi_tpu.ops.swe_kernels import (
        swe_step_padded,
        swe_step_padded_pallas,
    )

    hp = _rand((34, 30))
    ups = (_rand((34, 30), seed=1), _rand((34, 30), seed=2))
    Mus = (jnp.ones((32, 28), jnp.float32), jnp.ones((32, 28), jnp.float32))
    consts, dt, spacing = (1.0, 1.0), 1e-3, (0.1, 0.07)
    got = swe_step_padded_pallas((hp,) + ups, Mus, consts, dt, spacing)
    ref = swe_step_padded((hp,) + ups, Mus, consts, dt, spacing)
    for g, r in zip(got, ref):
        _close(g, r)


def test_swe_vmem_multi_step_compiled():
    # The whole-loop-in-VMEM coupled multi-step, compiled, vs the jnp
    # roll form (masked_swe_step — the one definition of the update).
    from rocm_mpi_tpu.ops.swe_kernels import (
        masked_swe_step,
        swe_coeffs,
        swe_multi_step,
    )

    h0 = _rand((32, 32))
    us0 = (jnp.zeros((32, 32), jnp.float32),) * 2
    gidx0 = jax.lax.broadcasted_iota(jnp.int32, (32, 32), 0)
    gidx1 = jax.lax.broadcasted_iota(jnp.int32, (32, 32), 1)
    Mus = (
        jnp.where(gidx0 >= 31, 0.0, 1.0).astype(jnp.float32),
        jnp.where(gidx1 >= 31, 0.0, 1.0).astype(jnp.float32),
    )
    dt, spacing = 2e-3, (0.1, 0.1)
    cH, cg = swe_coeffs(dt, spacing, 1.0, 1.0)
    ref_h, ref_us = h0, us0
    for _ in range(16):
        ref_h, ref_us = masked_swe_step(ref_h, ref_us, Mus, cH, cg)
    got_h, got_us = swe_multi_step(
        h0, us0, Mus, dt, spacing, 1.0, 1.0, 16, chunk=8
    )
    _close(got_h, ref_h)
    for g, r in zip(got_us, ref_us):
        _close(g, r)


def test_swe_deep_sweep_compiled():
    # The SWE deep-halo sweep's masked VMEM kernel on a 1-device mesh.
    from rocm_mpi_tpu.models.swe import SWEConfig, ShallowWater
    from rocm_mpi_tpu.parallel.deep_halo import make_swe_deep_sweep

    cfg = SWEConfig(
        global_shape=(64, 64), lengths=(10.0, 10.0), nt=8, warmup=0,
        dtype="f32", dims=(1, 1),
    )
    model = ShallowWater(cfg, devices=jax.devices()[:1])
    h, us = model.init_state()
    Mus = model.face_masks()
    ref_h, ref_us = model.advance_fn("ap")(
        jnp.copy(h), tuple(map(jnp.copy, us)), Mus, 8
    )
    sched = make_swe_deep_sweep(model.grid, 4, cfg.dt, cfg.spacing,
                                cfg.H0, cfg.g)
    P = jax.jit(sched.prepare)(h)
    sweep = jax.jit(sched.sweep)
    got_h, got_us = sweep(*sweep(h, us, P), P)
    _close(got_h, ref_h)
    for gu, ru in zip(got_us, ref_us):
        _close(gu, ru)


def test_swe_hide_strip_kernels_compiled():
    # The SWE hide variant's strip combination: the pytree-state overlap
    # decomposition with the coupled padded Pallas kernel per region —
    # under shard_map on a 1-device mesh, so the slab-shaped SWE kernels
    # compile on the chip even though the sharded hide path needs >= 2
    # devices to be selected organically.
    from jax import shard_map

    from rocm_mpi_tpu.ops.swe_kernels import (
        swe_step_padded,
        swe_step_padded_pallas,
    )
    from rocm_mpi_tpu.parallel.mesh import init_global_grid
    from rocm_mpi_tpu.parallel.overlap import make_overlap_step

    grid = init_global_grid(48, 48, dims=(1, 1), devices=jax.devices()[:1])
    dt, spacing = 1e-3, grid.spacing
    consts = (1.0, 1.0)

    def pu(Sp, Ml, lam, dt_, sp):
        del lam
        return swe_step_padded_pallas(Sp, Ml, consts, dt_, sp)

    local = make_overlap_step(grid, pu, (8, 8), mask_boundary=False)
    h = _rand((48, 48))
    us = (_rand((48, 48), seed=1), _rand((48, 48), seed=2))
    gi0 = jax.lax.broadcasted_iota(jnp.int32, (48, 48), 0)
    gi1 = jax.lax.broadcasted_iota(jnp.int32, (48, 48), 1)
    Mus = (
        jnp.where(gi0 >= 47, 0.0, 1.0).astype(jnp.float32),
        jnp.where(gi1 >= 47, 0.0, 1.0).astype(jnp.float32),
    )

    @jax.jit
    def step(h, u0, u1, M0, M1):
        return shard_map(
            lambda hl, u0l, u1l, M0l, M1l: local(
                (hl, u0l, u1l), (M0l, M1l), None, dt, spacing
            ),
            mesh=grid.mesh,
            in_specs=(grid.spec,) * 5,
            out_specs=(grid.spec,) * 3,
            check_vma=False,
        )(h, u0, u1, M0, M1)

    got = step(h, us[0], us[1], Mus[0], Mus[1])
    # Referee: the jnp padded form on the zero-padded whole block (the
    # 1-device ghost convention).
    pad = [(1, 1)] * 2
    Sp = tuple(jnp.pad(f, pad) for f in (h,) + us)
    ref = swe_step_padded(Sp, Mus, consts, dt, spacing)
    for g, r in zip(got, ref):
        _close(g, r)
