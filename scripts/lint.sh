#!/usr/bin/env bash
# graftlint gate: the repo's own shard-safety analyzer over the gate scope
# (rule catalog: docs/ANALYSIS.md; engine: rocm_mpi_tpu/analysis/).
#
# Fast (<5 s, stdlib-only AST walk) — run it BEFORE the test suite: it
# catches the donation-race / trace-purity / compat-drift / raw-timing
# bug classes that unit tests only see under the exact interleaving that
# bites.
#
# Also validates the committed measurement baselines still parse as known
# formats (telemetry regress --check-schema, docs/TELEMETRY.md): a
# hand-edited BASELINE/MULTICHIP file must fail here, not silently brick
# the perf-regression gate that reads it.
#
# Exit codes: 0 clean, 1 non-suppressed findings or schema problems,
# 2 usage/internal error. Extra args pass through to the analyzer
# (e.g. scripts/lint.sh --json, --select GL03).
set -u
cd "$(dirname "$0")/.."
# The gate never needs a device and must not hang on a flaky chip tunnel.
env JAX_PLATFORMS=cpu python -m rocm_mpi_tpu.analysis \
  rocm_mpi_tpu apps bench.py "$@" || exit $?
# Schema stage's ok-line goes to stderr so `scripts/lint.sh --json | jq`
# (the documented analyzer usage) still receives pure JSON on stdout;
# problems already print to stderr.
# BENCH_r*.json only exists once bench.py --suite has banked a suite on a
# chip — an empty trajectory must not read as a missing file. nullglob is
# scoped to THIS expansion only: the other baseline families must keep
# failing loudly (exit 2 "missing") if their files disappear.
shopt -s nullglob
bench_records=(BENCH_r*.json)
# Health-plane sidecars (heartbeat-rank*.json, postmortem-rank*.json,
# postmortem/bundle.json — docs/TELEMETRY.md "Health plane") are runtime
# artifacts: they exist only after a --health run or a watchdog verdict,
# under the default sink and wherever chip_watcher archived them. When
# present they must parse as their committed schema — a drifted writer
# would brick every watchdog/monitor reader at the next real incident.
health_records=(
  output/telemetry/heartbeat-rank*.json
  output/telemetry/postmortem-rank*.json
  output/telemetry/postmortem/postmortem-rank*.json
  output/telemetry/postmortem/bundle*.json
  docs/telemetry_r*/heartbeat-rank*.json
  docs/telemetry_r*/postmortem/postmortem-rank*.json
  docs/telemetry_r*/postmortem/bundle*.json
)
# Elastic-recovery artifacts (docs/RESILIENCE.md "Elastic recovery"),
# still inside the same nullglob scope: the supervisor's elastic.jsonl
# event sidecars and the checkpoint manifests' v2 topology metadata. A
# drifted elastic record bricks the monitor's SHRUNK badge; drifted
# manifest metadata bricks every template-less resume that plans a mesh
# from it — catch both here, not at the next real incident.
# (wildcard-bearing paths only: a literal path would survive nullglob
# and report "missing" when the artifact legitimately doesn't exist)
health_records+=(
  output/*/elastic.jsonl
  docs/telemetry_r*/elastic.jsonl
  output/*/manifest-*.json
  docs/telemetry_r*/manifest-*.json
)
shopt -u nullglob
env JAX_PLATFORMS=cpu python -m rocm_mpi_tpu.telemetry regress \
  --check-schema BASELINE.json MULTICHIP_r0*.json \
  ${bench_records[@]+"${bench_records[@]}"} \
  ${health_records[@]+"${health_records[@]}"} \
  docs/weak_scaling_*mechanics*.jsonl 1>&2 || exit $?
# Autotuner caches (docs/PERF.md "Autotuning"): the runtime cache and
# any chip_watcher-archived snapshots must parse as the committed schema
# AND every entry must clear the tuning traffic gate — a drifted writer
# (or a doctored over-budget "winner") fails here, not as a silent
# trace-time miss (or worse, a silently adopted waste config). Same
# nullglob discipline: caches exist only after a search ran.
shopt -s nullglob
tuning_caches=(
  output/tuning/cache*.json
  docs/telemetry_r*/tuning-cache*.json
)
shopt -u nullglob
if [ "${#tuning_caches[@]}" -gt 0 ]; then
  env JAX_PLATFORMS=cpu python -m rocm_mpi_tpu.tuning validate \
    "${tuning_caches[@]}" 1>&2 || exit $?
fi
# Compiled HBM-traffic gate (docs/PERF.md): lowers + audits every
# distributed step driver against perf/budgets.json on virtual CPU
# devices — the static roofline check; no accelerator, no timing.
exec env JAX_PLATFORMS=cpu python -m rocm_mpi_tpu.perf 1>&2
