#!/usr/bin/env bash
# graftlint gate: the repo's own shard-safety analyzer over the gate scope
# (rule catalog: docs/ANALYSIS.md; engine: rocm_mpi_tpu/analysis/).
#
# Run it BEFORE the test suite: the whole-program interprocedural pass
# (GL08 collective divergence, GL10 concurrency discipline, cross-module
# GL01 donation, GL09 sidecar atomicity, plus the per-file families)
# catches the bug classes unit tests only see under the exact
# interleaving — or the exact multi-host topology — that bites. Compared against the committed baseline
# (analysis/baseline.json: accepted findings never gate, NEW findings
# always do), and the machine-readable artifact is banked at
# output/lint/findings.json (schema-checked below; chip_watcher
# archives it per burst). `scripts/lint.sh --changed` is the fast dev
# loop (git-dirty files + import-graph neighbors only).
#
# Also validates the committed measurement baselines still parse as known
# formats (telemetry regress --check-schema, docs/TELEMETRY.md): a
# hand-edited BASELINE/MULTICHIP file must fail here, not silently brick
# the perf-regression gate that reads it.
#
# Exit codes: 0 clean, 1 non-suppressed findings or schema problems,
# 2 usage/internal error. Extra args pass through to the analyzer
# (e.g. scripts/lint.sh --json, --select GL03, --changed).
# Whole AST stage (interprocedural engine included) is bounded well
# under 60 s; the two compiled stages at the end (lowered audit +
# traffic gate) lower small CPU programs and stay inside the same
# budget.
set -u
cd "$(dirname "$0")/.."
# The gate never needs a device and must not hang on a flaky chip tunnel.
# --strict-suppressions: a `# graftlint: disable…` directive that
# covers no finding is itself a GL99 error (a dead directive silently
# blesses the next finding at its site).
env JAX_PLATFORMS=cpu python -m rocm_mpi_tpu.analysis \
  rocm_mpi_tpu apps bench.py \
  --baseline --strict-suppressions \
  --output output/lint/findings.json "$@" || exit $?
# Schema stage's ok-line goes to stderr so `scripts/lint.sh --json | jq`
# (the documented analyzer usage) still receives pure JSON on stdout;
# problems already print to stderr.
# BENCH_r*.json only exists once bench.py --suite has banked a suite on a
# chip — an empty trajectory must not read as a missing file. nullglob is
# scoped to THIS expansion only: the other baseline families must keep
# failing loudly (exit 2 "missing") if their files disappear.
shopt -s nullglob
bench_records=(BENCH_r*.json)
# Health-plane sidecars (heartbeat-rank*.json, postmortem-rank*.json,
# postmortem/bundle.json — docs/TELEMETRY.md "Health plane") are runtime
# artifacts: they exist only after a --health run or a watchdog verdict,
# under the default sink and wherever chip_watcher archived them. When
# present they must parse as their committed schema — a drifted writer
# would brick every watchdog/monitor reader at the next real incident.
health_records=(
  output/telemetry/heartbeat-rank*.json
  output/telemetry/postmortem-rank*.json
  output/telemetry/postmortem/postmortem-rank*.json
  output/telemetry/postmortem/bundle*.json
  docs/telemetry_r*/heartbeat-rank*.json
  docs/telemetry_r*/postmortem/postmortem-rank*.json
  docs/telemetry_r*/postmortem/bundle*.json
)
# Elastic-recovery artifacts (docs/RESILIENCE.md "Elastic recovery" and
# §7), still inside the same nullglob scope: the supervisor's
# elastic.jsonl event sidecars (shrink AND grow records — chip_watcher
# archives drill sidecars as elastic-*.jsonl) and the checkpoint
# manifests' v2 topology metadata. A drifted elastic record bricks the
# monitor's SHRUNK/GROWN badges; drifted manifest metadata bricks every
# template-less resume that plans a mesh from it — catch both here, not
# at the next real incident. The archived telemetry rank streams ride
# along for the preempt.*/ckpt.* event families (the preemption decision
# trail and the storage-fault plane's retry/degraded records).
# (wildcard-bearing paths only: a literal path would survive nullglob
# and report "missing" when the artifact legitimately doesn't exist)
health_records+=(
  output/*/elastic*.jsonl
  docs/telemetry_r*/elastic*.jsonl
  output/*/manifest-*.json
  docs/telemetry_r*/manifest-*.json
  docs/telemetry_r*/telemetry-rank*.jsonl
)
# Serving sidecars (docs/SERVING.md): the bin manifest + request trace
# apps/serve.py banks per run (and chip_watcher archives per burst),
# plus the request-plane hardening artifacts — the append-only
# quarantine.jsonl poison ledger and the chaos soak's soak-report.json
# (docs/RESILIENCE.md §8). A drifted writer bricks the schema-checked
# serving accounting the next time anyone audits a trace's compile
# count — or reads a poisoned service's incident ledger — catch it
# here. (wildcard-bearing paths only, same nullglob discipline)
health_records+=(
  output/*/serve-manifest*.json
  output/*/serve-requests*.jsonl
  output/*/quarantine*.jsonl
  output/*/soak-report*.json
  docs/telemetry_r*/serve-manifest*.json
  docs/telemetry_r*/serve-requests*.jsonl
  docs/telemetry_r*/quarantine*.jsonl
  docs/telemetry_r*/soak-report*.json
)
# Fleet sidecars (docs/SERVING.md "The fleet"): the router's durable
# ticket journal and the merged fleet report apps/fleet.py banks. The
# journal is the replay-reconciliation record — a drifted writer means
# a replica kill can no longer be reconciled from disk; same stakes,
# same gate.
health_records+=(
  output/*/fleet-journal*.jsonl
  output/*/fleet-report*.json
  docs/telemetry_r*/fleet-journal*.jsonl
  docs/telemetry_r*/fleet-report*.json
)
# Request-tracing artifacts (docs/TELEMETRY.md "Request tracing"): the
# per-request rmt-trace-report documents `telemetry trace --out` (and
# the fleet/soak drills) bank. A drifted report writer bricks the
# tail-latency triage the next time anyone decomposes a slow request.
health_records+=(
  output/*/trace-report*.json
  docs/telemetry_r*/trace-report*.json
)
# The graftlint artifacts: the findings document stage 1 just banked
# (plus any chip_watcher-archived copies) and the committed baseline.
# A drifted reporter or a hand-mangled baseline must fail HERE, not
# silently mis-gate the next analysis run. (findings*.json stays in the
# nullglob group: a --baseline-write invocation exits before writing
# one, and that must not read as "missing".)
health_records+=(
  output/lint/findings*.json
  docs/telemetry_r*/lint-findings*.json
)
shopt -u nullglob
env JAX_PLATFORMS=cpu python -m rocm_mpi_tpu.telemetry regress \
  --check-schema BASELINE.json MULTICHIP_r0*.json \
  rocm_mpi_tpu/analysis/baseline.json \
  rocm_mpi_tpu/perf/budgets.json \
  ${bench_records[@]+"${bench_records[@]}"} \
  ${health_records[@]+"${health_records[@]}"} \
  docs/weak_scaling_*mechanics*.jsonl 1>&2 || exit $?
# Autotuner caches (docs/PERF.md "Autotuning"): the runtime cache and
# any chip_watcher-archived snapshots must parse as the committed schema
# AND every entry must clear the tuning traffic gate — a drifted writer
# (or a doctored over-budget "winner") fails here, not as a silent
# trace-time miss (or worse, a silently adopted waste config). Same
# nullglob discipline: caches exist only after a search ran.
shopt -s nullglob
tuning_caches=(
  output/tuning/cache*.json
  docs/telemetry_r*/tuning-cache*.json
)
shopt -u nullglob
if [ "${#tuning_caches[@]}" -gt 0 ]; then
  env JAX_PLATFORMS=cpu python -m rocm_mpi_tpu.tuning validate \
    "${tuning_caches[@]}" 1>&2 || exit $?
fi
# Lowered-program audit (docs/ANALYSIS.md "The lowered-program audit"):
# compiles all three workloads' steady-state drivers on virtual CPU
# devices and proves (a) the collective sequence is identical across
# rank-roles (no collective under a lowered conditional, channel-pinned
# order, sane permute pair structure) and (b) every GL01-declared
# donation actually aliased — the ground truth the AST engine's GL08/
# GL01 verdicts approximate.
env JAX_PLATFORMS=cpu python -m rocm_mpi_tpu.analysis.lowered 1>&2 \
  || exit $?
# Compiled HBM-traffic gate (docs/PERF.md): lowers + audits every
# distributed step driver against perf/budgets.json on virtual CPU
# devices — the static roofline check; no accelerator, no timing.
exec env JAX_PLATFORMS=cpu python -m rocm_mpi_tpu.perf 1>&2
