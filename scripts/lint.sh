#!/usr/bin/env bash
# graftlint gate: the repo's own shard-safety analyzer over the gate scope
# (rule catalog: docs/ANALYSIS.md; engine: rocm_mpi_tpu/analysis/).
#
# Fast (<5 s, stdlib-only AST walk) — run it BEFORE the test suite: it
# catches the donation-race / trace-purity / compat-drift bug classes that
# unit tests only see under the exact interleaving that bites.
#
# Exit codes: 0 clean, 1 non-suppressed findings, 2 usage/internal error.
# Extra args pass through (e.g. scripts/lint.sh --json, --select GL03).
set -u
cd "$(dirname "$0")/.."
# The gate never needs a device and must not hang on a flaky chip tunnel.
exec env JAX_PLATFORMS=cpu python -m rocm_mpi_tpu.analysis \
  rocm_mpi_tpu apps bench.py "$@"
