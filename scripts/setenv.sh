#!/usr/bin/env bash
# Environment configuration — the analog of the reference's scripts/setenv.sh
# (module loads + the ROCm-aware/host-staged MPI toggle,
# /root/reference/scripts/setenv.sh). On TPU there are no modules to load;
# the knobs that remain:
#
#   RMT_HALO_TRANSPORT=ici   # device-direct collectives over the
#                            # interconnect (the ROCm-aware / GPU-direct
#                            # analog; default)
#   RMT_HALO_TRANSPORT=host  # host-staged oracle path (the
#                            # IGG_ROCMAWARE_MPI=0 analog) — single process,
#                            # 'shard' variant only
#   RMT_DISTRIBUTED=1        # multi-host: jax.distributed.initialize()
#                            # (the srun/PMIx analog)
#
# Source this before running apps: `source scripts/setenv.sh [host]`

if [ "${1:-}" = "host" ]; then
  export RMT_HALO_TRANSPORT=host
elif [ "${1:-}" = "ici" ]; then
  export RMT_HALO_TRANSPORT=ici
else
  # No explicit argument: respect an already-exported RMT_HALO_TRANSPORT
  # (e.g. `RMT_HALO_TRANSPORT=host scripts/run.sh perf`), default ici.
  export RMT_HALO_TRANSPORT="${RMT_HALO_TRANSPORT:-ici}"
fi

# Simulated multi-chip CPU mesh for development without hardware
# (the reference has no such affordance; SURVEY.md §4.5):
#   export JAX_PLATFORMS=cpu
#   export XLA_FLAGS="--xla_force_host_platform_device_count=8"

echo "RMT_HALO_TRANSPORT=${RMT_HALO_TRANSPORT}"
