"""Kernel-form A/B for the VMEM-resident multi-step kernel (the scored path).

Times candidate bodies of ops.pallas_kernels._multi_step_kernel at the
benchmark geometry (252² f32, chunk=256) within ONE process, so tunnel
run-to-run variance (~10-20 %) cancels and the comparison is the within-run
protocol of docs/perstep_bounds_r3.txt. The baseline form is measured first
AND last to expose drift.

Candidates:

  ac       — the production A/c form: T' = A∘T + Σ_ax c_ax∘(roll pair),
             prologue-hoisted coefficients (ops/pallas_kernels.py).
  eqc      — equal-spacing specialization (dx == dy, true of the benchmark
             geometry): the per-axis coefficients collapse to ONE array c,
             T' = A∘T + c∘(r₋x + r₊x + r₋y + r₊y) — one fewer VPU multiply
             per step.
  pad_ac   — the ac form on a 256²-padded layout: every vreg tile is full
             and the ±1 rolls are aligned power-of-two shifts. The pad ring
             carries Cm = 0, so pad cells never update and the interior is
             bit-identical to the 252² program (roll wraparound only ever
             reaches Cm==0 cells — same argument as the production kernel's
             Dirichlet ring).
  pad_eqc  — both.
  conly    — eqc minus the A array: T' = T + c∘(s − 2·ndim·T). Same op
             count as eqc at one fewer VMEM operand read per step (T and c
             instead of T, A, c); Dirichlet hold: c==0 ⇒ T'==T bitwise.
  pad_conly — conly on the 256²-padded layout.

Each candidate is cross-checked against the production form (256 steps,
allclose) before timing. Run on the chip:

    python scripts/bench_kernel_forms.py [timed_steps]

Output appended to stdout; the winning form gets productized in
ops/pallas_kernels.py with the measured numbers in its docstring.
"""

import functools
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from rocm_mpi_tpu.utils.compat import pallas as pl
from rocm_mpi_tpu.utils.compat import pallas_tpu as pltpu

from rocm_mpi_tpu.ops.pallas_kernels import edge_masked_cm
from rocm_mpi_tpu.utils import metrics
from rocm_mpi_tpu.utils.backend import enable_persistent_cache, require_accelerator

N = 252
PAD = 256
CHUNK = 256
WARMUP = 32_768
LAM, CP0 = 1.0, 1.0


def _body_ac(T, cs, A):
    acc = A * T
    for ax in range(T.ndim):
        acc = acc + cs[ax] * (jnp.roll(T, -1, ax) + jnp.roll(T, 1, ax))
    return acc


def _body_eqc(T, c, A):
    s = None
    for ax in range(T.ndim):
        r = jnp.roll(T, -1, ax) + jnp.roll(T, 1, ax)
        s = r if s is None else s + r
    return A * T + c * s


def _body_conly(T, c, nax):
    s = None
    for ax in range(T.ndim):
        r = jnp.roll(T, -1, ax) + jnp.roll(T, 1, ax)
        s = r if s is None else s + r
    return T + c * (s - (2.0 * nax) * T)


def _kernel(T_ref, Cm_ref, out_ref, *, inv_d2, form):
    Cm = Cm_ref[:]
    if form == "ac":
        cs = [Cm * inv for inv in inv_d2]
        A = 1.0 - 2.0 * functools.reduce(lambda a, b: a + b, cs)
        body = lambda _, T: _body_ac(T, cs, A)
    elif form == "eqc":
        assert all(inv == inv_d2[0] for inv in inv_d2)
        c = Cm * inv_d2[0]
        A = 1.0 - 2.0 * len(inv_d2) * c
        body = lambda _, T: _body_eqc(T, c, A)
    else:  # conly
        assert all(inv == inv_d2[0] for inv in inv_d2)
        c = Cm * inv_d2[0]
        body = lambda _, T: _body_conly(T, c, len(inv_d2))
    out_ref[:] = lax.fori_loop(0, CHUNK, body, T_ref[:], unroll=True)


def make_advance(shape, inv_d2, form):
    call = pl.pallas_call(
        functools.partial(_kernel, inv_d2=inv_d2, form=form),
        out_shape=jax.ShapeDtypeStruct(shape, jnp.float32),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
    )

    @functools.partial(jax.jit, donate_argnums=0)
    def advance(T, Cm, n):
        return lax.fori_loop(0, n // CHUNK, lambda _, x: call(x, Cm), T)

    return advance


def main():
    enable_persistent_cache()
    timed = int(sys.argv[1]) if len(sys.argv) > 1 else 8_388_608
    timed -= timed % CHUNK
    require_accelerator("bench_kernel_forms.py")
    dev = jax.devices()[0]
    print(f"device: {dev} | {N}² f32 chunk={CHUNK} | warmup {WARMUP} | "
          f"timed {timed}")

    spacing = 10.0 / N
    inv = 1.0 / (spacing * spacing)
    key = jax.random.PRNGKey(0)
    T0 = jax.random.uniform(key, (N, N), jnp.float32)
    Cp = jnp.full((N, N), CP0, jnp.float32)
    # dt small enough to stay stable over millions of steps
    dt = spacing * spacing * CP0 / LAM / 4.1
    Cm = edge_masked_cm(T0, Cp, LAM, dt)

    pad = ((0, PAD - N), (0, PAD - N))
    T0p = jnp.pad(T0, pad)
    Cmp = jnp.pad(Cm, pad)

    cases = {
        "ac": ((N, N), (inv, inv), "ac", T0, Cm, None),
        "eqc": ((N, N), (inv, inv), "eqc", T0, Cm, None),
        "conly": ((N, N), (inv, inv), "conly", T0, Cm, None),
        "pad_ac": ((PAD, PAD), (inv, inv), "ac", T0p, Cmp, (N, N)),
        "pad_eqc": ((PAD, PAD), (inv, inv), "eqc", T0p, Cmp, (N, N)),
        "pad_conly": ((PAD, PAD), (inv, inv), "conly", T0p, Cmp, (N, N)),
    }

    order = ["ac", "eqc", "conly", "pad_ac", "pad_eqc", "pad_conly", "ac"]
    advances = {}  # one compile per case; the repeat reuses it
    ref = None
    results = {}
    for i, name in enumerate(order):
        shape, inv_d2, form, T_init, Cm_case, crop = cases[name]
        if name not in advances:
            advances[name] = make_advance(shape, inv_d2, form)
        adv = advances[name]
        out = np.asarray(adv(jnp.copy(T_init), Cm_case, CHUNK))
        if crop:
            out = out[: crop[0], : crop[1]]
        if ref is None:
            ref = out  # first 'ac' run doubles as the correctness referee
        np.testing.assert_allclose(out, ref, rtol=1e-6, atol=1e-7,
                                   err_msg=f"form {name} diverges")
        T = adv(jnp.copy(T_init), Cm_case, WARMUP)
        timer = metrics.Timer()
        timer.tic(T)
        T = adv(T, Cm_case, timed)
        w = timer.toc(T)
        ns = w / timed * 1e9
        gpts = N * N / (w / timed) / 1e9
        tag = f"{name}[{i}]"
        results.setdefault(name, []).append(ns)
        print(f"{tag:12s} {ns:8.2f} ns/step   {gpts:8.2f} Gpts/s (252² pts)",
              flush=True)

    base = min(results["ac"])
    for name in order[1:-1]:
        ns = min(results[name])
        print(f"{name:10s} vs ac: {base / ns:.3f}x  ({base:.1f} -> {ns:.1f} ns)")


if __name__ == "__main__":
    main()
