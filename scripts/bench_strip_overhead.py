"""Strip-assembly overhead of the hide program (VERDICT r3 #6).

The hide variant's per-shard work is the boundary-slab/interior
decomposition of parallel.overlap.make_overlap_step: per step it launches
one region kernel per slab plus the interior and concatenates the pieces —
machinery whose *benefit* (hiding the exchange) needs ≥2 chips, but whose
*cost* does not: on one chip the same decomposition can be timed against
the monolithic whole-shard kernel the perf variant runs.

A/B protocol (within one process, the docs/perstep_bounds_r3.txt style):
for each shard size × b_width, time
  mono  — the per-step Cm-masked whole-shard program
          (ops.pallas_kernels.masked_step, what perf runs unsharded), and
  strip — make_overlap_step on a 1-device grid with the same fused_step_cm
          region kernel and the same Cm contract (exactly the multi-device
          hide program's per-shard work; the 1-device ppermute is a no-op,
          so the difference IS the strip machinery: slab slicing, extra
          kernel launches, concatenation).
Overhead % = strip/mono − 1. This is the data behind the b_width default
(config.py's (32,4), the reference's knob, hide.jl:42 — untimed until now).

Run on the chip:  python scripts/bench_strip_overhead.py [timed_steps]
Output committed as docs/strip_overhead_r4.txt.
"""

import functools
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

TIMED_DEFAULT = 65_536
WARMUP = 4_096


from rocm_mpi_tpu.utils.backend import (
    apply_platform_override,
    enable_persistent_cache,
    require_accelerator,
)  # noqa: E402


def main(argv=None) -> int:
    argv = list(argv) if argv else []
    # Queue runs pass --require-accelerator so a mid-queue CPU fallback
    # exits nonzero (→ INCOMPLETE artifact, retried) instead of promoting
    # interpret-mode numbers as the completed chip measurement.
    require_accel = "--require-accelerator" in argv
    argv = [a for a in argv if a != "--require-accelerator"]
    timed = int(argv[0]) if argv else TIMED_DEFAULT
    apply_platform_override()
    enable_persistent_cache()
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax import lax

    from rocm_mpi_tpu.utils.compat import shard_map

    from rocm_mpi_tpu.config import DiffusionConfig
    from rocm_mpi_tpu.models import HeatDiffusion
    from rocm_mpi_tpu.ops.pallas_kernels import fused_step_cm, masked_step
    from rocm_mpi_tpu.parallel.overlap import (
        effective_b_width,
        make_overlap_step,
    )
    from rocm_mpi_tpu.utils import metrics

    if require_accel:
        require_accelerator("bench_strip_overhead.py")
    dev = jax.devices()[0]
    on_cpu = dev.platform == "cpu"
    if on_cpu:
        timed = min(timed, 64)
        print("NOTE: no accelerator — interpret-mode mechanics run, "
              "overhead numbers are NOT meaningful", flush=True)
    print(f"device: {dev} | f32 | warmup {WARMUP if not on_cpu else 8} "
          f"| timed {timed} steps/case", flush=True)
    warmup = WARMUP if not on_cpu else 8

    shard_sizes = [64, 128, 252, 504]
    b_widths = [(32, 4), (8, 8), (16, 16), (32, 32), (4, 4)]

    print(f"{'shard':>6} {'b_width':>9} {'mono µs':>9} {'strip µs':>9} "
          f"{'overhead':>9}")
    for n in shard_sizes:
        cfg = DiffusionConfig(
            global_shape=(n, n), lengths=(10.0, 10.0), nt=timed + warmup,
            warmup=warmup, dtype="f32", dims=(1, 1),
        )
        model = HeatDiffusion(cfg)
        grid = model.grid
        T0, Cp = model.init_state()
        dt = cfg.jax_dtype(cfg.dt)
        prep = model._cm_prepare()

        def time_advance(step_local):
            @functools.partial(jax.jit, donate_argnums=0)
            def advance(T, Cp, k):
                Cm = prep(Cp, cfg.lam, dt)
                body = lambda _, t: shard_map(
                    step_local, mesh=grid.mesh,
                    in_specs=(grid.spec, grid.spec), out_specs=grid.spec,
                    check_vma=False,
                )(t, Cm)
                return lax.fori_loop(0, k, body, T)

            T = advance(jnp.copy(T0), Cp, warmup)
            timer = metrics.Timer()
            timer.tic(T)
            T = advance(T, Cp, timed)
            w = timer.toc(T)
            return w / timed, np.asarray(T)

        # mono: the whole-shard Cm-masked kernel (the perf program).
        mono_t, mono_out = time_advance(
            lambda t, cm: masked_step(t, cm, cfg.spacing)
        )
        for bw in b_widths:
            local = make_overlap_step(
                grid,
                lambda tp, cm, lam, dt_, sp: fused_step_cm(tp, cm, sp),
                bw,
                mask_boundary=False,
            )
            strip_t, strip_out = time_advance(
                lambda t, cm: local(t, cm, cfg.lam, dt, cfg.spacing)
            )
            # Same trajectory: the strip program must be numerically
            # identical to the monolithic one (1-device ghosts are zeros
            # either way; Cm zeros hold the edge) — otherwise the timing
            # compares different programs.
            np.testing.assert_allclose(
                strip_out, mono_out, rtol=2e-6, atol=1e-7,
                err_msg=f"strip != mono at {n}² b_width={bw}",
            )
            eff = effective_b_width(grid.local_shape, bw)
            print(
                f"{n:6d} {str(eff):>9} {mono_t * 1e6:9.3f} "
                f"{strip_t * 1e6:9.3f} {strip_t / mono_t - 1.0:9.1%}",
                flush=True,
            )
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
