"""Per-step schedule bounds on one chip — the measured-floor evidence.

The reference-parity per-step rungs (one whole-field sweep + one exchange
per step, diffusion_2D_perf.jl:47-52) are bounded on TPU by two hardware
floors this script measures directly (VERDICT r2 ask #1b: "split dispatch
RTT vs collective latency vs kernel time, then attack the dominant term"):

1. 12288²-class (HBM-resident): the achievable HBM rate through this
   stack. Measured via (a) an XLA-fused whole-array negate (the simplest
   2-pass program XLA can emit), (b) a Pallas striped copy (the pipeline's
   own ceiling), (c) the production per-step kernel. A per-step schedule
   pays >= 3 whole-array passes (read T, read Cm/Cp, write T') by
   definition of T_eff, so T_eff can never exceed the achieved rate —
   temporal blocking (k steps per sweep) is the only way past it, which is
   why the framework's large-grid flagship is run_hbm_blocked, not perf.

2. 252²-class (VMEM-resident): the kernel-launch floor. multi_step_cm
   with k unrolled steps per launch is timed for k = 1..32; a linear fit
   time(k) = overhead + k*step gives the fixed per-launch cost. The
   per-step schedule pays `overhead` every step by construction; the
   VMEM-resident whole-loop kernel pays it once per 256 steps. On one
   chip there is no inter-chip collective in either path — what deep-halo
   sweeps amortize here is exactly this launch floor (k x fewer launches),
   and on a pod slice the same k divides the number of latency-bound halo
   exchanges.

Run on the chip:  python scripts/bench_bounds.py [N]
Committed output: docs/perstep_bounds_r3.txt
"""

import functools
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

import jax
import jax.numpy as jnp
from jax import lax
from rocm_mpi_tpu.utils.compat import pallas as pl
from rocm_mpi_tpu.utils.compat import pallas_tpu as pltpu

import rocm_mpi_tpu.ops.pallas_kernels as pk
from rocm_mpi_tpu.utils import metrics


def timeit(fn, T, C, steps, warm):
    # The trip count is TRACED so the warm and timed windows share one
    # compiled program — with a static count the timed call would include
    # a recompile (the exact mistake advance_fn's docstring warns about).
    @functools.partial(jax.jit, donate_argnums=0)
    def adv(T, C, n):
        return lax.fori_loop(0, n, lambda _, x: fn(x, C), T)

    T = adv(T, C, warm)
    t = metrics.Timer()
    t.tic(T)
    T = adv(T, C, steps)
    return t.toc(T) / steps


def hbm_bounds(n=12288, steps=60, warm=10):
    print(f"== HBM-resident per-step bounds at {n}² f32 "
          f"({n * n * 4 / 1e6:.0f} MB/pass) ==")
    T0 = jax.random.uniform(jax.random.PRNGKey(0), (n, n), jnp.float32)
    Cp = 1.0 + jax.random.uniform(jax.random.PRNGKey(1), (n, n), jnp.float32)
    Cm = pk.edge_masked_cm(T0, Cp, 1.0, 1e-7)
    spacing = (10.0 / n, 10.0 / n)
    P = n * n * 4 / 1e9  # GB per whole-array pass

    per = timeit(lambda T, C: -T, jnp.copy(T0), Cm, steps, warm)
    print(f"  XLA negate (2 passes)          {per * 1e6:9.1f} us  "
          f"actual {2 * P / per:6.1f} GB/s")

    def copy_kernel(a_ref, o_ref):
        o_ref[:] = a_ref[:]

    tm = 32
    spec = pl.BlockSpec((tm, n), lambda i: (i, 0), memory_space=pltpu.VMEM)
    copy = lambda T, C: pl.pallas_call(
        copy_kernel, out_shape=jax.ShapeDtypeStruct((n, n), jnp.float32),
        grid=(n // tm,), in_specs=[spec], out_specs=spec)(T)
    per = timeit(copy, jnp.copy(T0), Cm, steps, warm)
    print(f"  Pallas striped copy (2 passes) {per * 1e6:9.1f} us  "
          f"actual {2 * P / per:6.1f} GB/s")

    for tm in (16, 32):
        f = lambda T, C: pk.masked_step(T, C, spacing, tm=tm)
        per = timeit(f, jnp.copy(T0), Cm, steps, warm)
        # tm rows of output re-read (tm+2g) rows of T + tm of Cm per stripe
        passes = (tm + 16) / tm + 2
        print(f"  per-step kernel tm={tm:3d}         {per * 1e6:9.1f} us  "
              f"actual {passes * P / per:6.1f} GB/s  "
              f"T_eff {3 * P / per:6.1f} GB/s  {n * n / per / 1e9:6.2f} Gpts/s")
    print("  -> a 3-pass-per-step schedule is capped at T_eff ~= the "
          "achieved rate above;")
    print("     the framework's way past it is temporal blocking "
          "(run_hbm_blocked), not a faster per-step kernel.")


def dma_sweep(shapes=(2048, 4096, 8192, 12288), tms=(16, 32, 64, 128),
              steps=60, warm=10):
    """Pure-DMA Pallas copy across shapes and stripe heights (VERDICT r3
    weak #2: one more independent probe of "the part can't stream
    faster"). A copy does no arithmetic — its rate IS the achievable
    HBM↔VMEM stream rate of this stack at that transfer size; if ANY
    (shape, tm) cell beats the stream ceiling claimed by the per-step
    analysis, the claim was wrong."""
    print("\n== pure-DMA Pallas copy sweep (GB/s actual, 2 passes) ==")
    print(f"{'n':>7} " + "".join(f"tm={tm:<6d}" for tm in tms))

    def copy_kernel(a_ref, o_ref):
        o_ref[:] = a_ref[:]

    for n in shapes:
        T0 = jax.random.uniform(jax.random.PRNGKey(0), (n, n), jnp.float32)
        P = n * n * 4 / 1e9
        cells = []
        for tm in tms:
            spec = pl.BlockSpec(
                (tm, n), lambda i: (i, 0), memory_space=pltpu.VMEM
            )
            copy = lambda T, C: pl.pallas_call(
                copy_kernel,
                out_shape=jax.ShapeDtypeStruct((n, n), jnp.float32),
                grid=(n // tm,), in_specs=[spec], out_specs=spec)(T)
            per = timeit(copy, jnp.copy(T0), None, steps, warm)
            cells.append(f"{2 * P / per:6.1f}   ")
        print(f"{n:7d} " + "".join(cells), flush=True)


def launch_floor(n=252, reps=200_000):
    print(f"\n== VMEM-resident launch floor at {n}² f32 ==")
    T0 = jax.random.uniform(jax.random.PRNGKey(0), (n, n), jnp.float32)
    Cp = 1.0 + jax.random.uniform(jax.random.PRNGKey(1), (n, n), jnp.float32)
    Cm = pk.edge_masked_cm(T0, Cp, 1.0, 1e-7)
    spacing = (10.0 / n, 10.0 / n)
    ks = (1, 2, 4, 8, 16, 32)
    per_launch = {}
    for k in ks:
        f = lambda T, C, k=k: pk.multi_step_cm(T, C, spacing, k)
        launches = max(reps // k, 4000)
        per = timeit(f, jnp.copy(T0), Cm, launches, max(launches // 10, 500))
        per_launch[k] = per
        print(f"  k={k:3d} unrolled steps/launch   {per * 1e6:9.3f} us/launch "
              f" = {per / k * 1e6:7.3f} us/step", flush=True)
    # least-squares fit: time(k) = overhead + k*step_cost
    import numpy as np

    A = np.array([[1.0, k] for k in ks])
    y = np.array([per_launch[k] for k in ks])
    (a, b), *_ = np.linalg.lstsq(A, y, rcond=None)
    print(f"  fit: time(k) ~= {a * 1e6:.3f} us/launch + {b * 1e6:.3f} us/step")
    print(f"  -> the per-step schedule pays the ~{a * 1e6:.2f} us launch "
          "floor every step; deep-halo sweeps pay it once per k steps "
          "(and on a pod slice also 1/k of the halo exchanges), the "
          "VMEM-resident loop once per 256.")


if __name__ == "__main__":
    from rocm_mpi_tpu.utils.backend import enable_persistent_cache

    enable_persistent_cache()
    if jax.devices()[0].platform == "cpu":
        sys.exit("bench_bounds.py needs an accelerator backend")
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 12288
    hbm_bounds(n)
    dma_sweep()
    launch_floor()
