"""bf16-vs-f32 error at run length (VERDICT r3 #4).

The bf16 fast path is a *labeled precision trade* the user opts into with
--dtype bf16 (BASELINE.md): it halves per-step memory traffic. Round 3
documented its error at 4 steps only; this harness characterizes the
error-vs-steps curve out to the reference's full 1000-step run
(/root/reference/scripts/diffusion_2D_perf.jl:47 — nt=1000) at the
acceptance geometry (252²), so the trade's cost is known at the run length
the claim covers.

Protocol: advance the SAME per-step masked program (the schedule --dtype
selects, models.diffusion variant 'perf' → ops.pallas_kernels.masked_step)
in f32 and in bf16 from the same Gaussian IC; at log-spaced checkpoints
report the relative L2 error, the max pointwise error against the field
scale, and the peak-temperature drift (the max(T) decay invariant,
hide.jl:115). Measured finding: the error GROWS with run length — once
per-step field changes fall below bf16's 8-bit mantissa resolution, the
storage rounding accumulates as systematic drift (the bf16 peak decays
slower than f32's) rather than averaging out, so the trade is priced per
run length, not per step.

Run:  python scripts/bench_bf16_error.py            # on the chip
      JAX_PLATFORMS=cpu python scripts/bench_bf16_error.py --steps 128
                                                    # interpret-mode CPU
Output: one table row per checkpoint (committed as docs/bf16_error_r4.txt);
tests/test_bf16_error.py pins the 128-step bound from the same machinery.
"""

import argparse
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))


from rocm_mpi_tpu.utils.backend import (
    apply_platform_override,
    enable_persistent_cache,
    require_accelerator,
)  # noqa: E402


def error_curve(n=252, checkpoints=(1, 2, 4, 8, 16, 32, 64, 128, 256, 512,
                                    1000), schedule="perf",
                vmem_chunk=None):
    """[(steps, rel_l2, rel_max, peak_f32, peak_bf16), ...] at n² — shared
    by the chip harness and the CPU test.

    schedule "perf": the per-step masked program (state rounds to storage
    dtype every step — the reference-parity schedule, advanced
    incrementally). schedule "vmem": the whole-loop-in-VMEM multi-step
    kernel, where bf16 is storage-only — f32 in-kernel compute, ONE
    rounding per chunk — so each checkpoint is a fresh run from the IC at
    that step count (chunk = gcd(steps, 256), or `vmem_chunk` to pin the
    rounding cadence — interpret-mode tracing cost grows superlinearly
    with the unroll, so the CPU test pins chunk=8; the cadence is part of
    what's measured, so incremental advance would distort it).
    """
    import jax
    import numpy as np

    from rocm_mpi_tpu.config import DiffusionConfig
    from rocm_mpi_tpu.models import HeatDiffusion

    if schedule not in ("perf", "vmem"):
        raise ValueError(f"schedule must be perf|vmem, got {schedule!r}")

    models = {}
    for dtype in ("f32", "bf16"):
        cfg = DiffusionConfig(
            global_shape=(n, n), lengths=(10.0, 10.0),
            nt=max(checkpoints), warmup=0, dtype=dtype, dims=(1, 1),
        )
        models[dtype] = HeatDiffusion(cfg)

    rows = []
    if schedule == "perf":
        states = {d: m.init_state() for d, m in models.items()}
        advances = {d: m.advance_fn("perf") for d, m in models.items()}
        done = 0
        for ck in checkpoints:
            delta = ck - done
            out = {}
            for dtype in ("f32", "bf16"):
                T, Cp = states[dtype]
                T = advances[dtype](T, Cp, delta)
                states[dtype] = (T, Cp)
                out[dtype] = T
            done = ck
            rows.append(_error_row(ck, out["f32"], out["bf16"]))
    else:
        for ck in checkpoints:
            chunk = None if vmem_chunk is None else min(vmem_chunk, ck)
            out = {}
            for dtype in ("f32", "bf16"):
                m = models[dtype]
                r = m.run_vmem_resident(nt=ck, warmup=0, chunk=chunk)
                out[dtype] = r.T
            rows.append(_error_row(ck, out["f32"], out["bf16"]))
    return rows


def _error_row(ck, a_dev, b_dev):
    import numpy as np

    a = np.asarray(a_dev, dtype=np.float64)
    b = np.asarray(b_dev, dtype=np.float64)
    rel_l2 = float(np.linalg.norm(b - a) / np.linalg.norm(a))
    rel_max = float(np.abs(b - a).max() / np.abs(a).max())
    return (ck, rel_l2, rel_max, float(a.max()), float(b.max()))


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--n", type=int, default=252)
    p.add_argument("--steps", type=int, default=1000,
                   help="last checkpoint (smaller for interpret-mode runs)")
    p.add_argument("--schedule", default="perf", choices=["perf", "vmem"],
                   help="perf: per-step (rounds to storage dtype every "
                   "step); vmem: multi-step kernel (bf16 storage-only, "
                   "f32 compute, one rounding per chunk)")
    p.add_argument("--vmem-chunk", type=int, default=None,
                   help="pin the vmem schedule's rounding cadence "
                   "(interpret-mode runs need a small chunk — tracing "
                   "cost grows superlinearly with the unroll)")
    p.add_argument("--require-accelerator", action="store_true",
                   help="exit nonzero on the CPU fallback (queue runs: a "
                   "chip-labeled artifact must never hold interpret-mode "
                   "curves)")
    args = p.parse_args(argv)

    apply_platform_override()
    enable_persistent_cache()
    import jax

    if args.require_accelerator:
        require_accelerator("bench_bf16_error.py")
    plat = jax.devices()[0].platform
    print(f"device: {jax.devices()[0]} ({plat}); {args.n}² schedule="
          f"{args.schedule}, f32 vs bf16 from the same Gaussian IC",
          flush=True)
    if plat == "cpu":
        print("NOTE: interpret-mode Pallas (no accelerator) — error values "
              "are valid, rates are not measured here", flush=True)
    cks = [c for c in (1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1000)
           if c <= args.steps]
    if cks[-1] != args.steps:
        cks.append(args.steps)
    print(f"{'steps':>6}  {'rel L2':>10}  {'rel max':>10}  "
          f"{'max(T) f32':>12}  {'max(T) bf16':>12}")
    for ck, l2, mx, pa, pb in error_curve(args.n, tuple(cks),
                                          schedule=args.schedule,
                                          vmem_chunk=args.vmem_chunk):
        print(f"{ck:6d}  {l2:10.4%}  {mx:10.4%}  {pa:12.6f}  {pb:12.6f}",
              flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
