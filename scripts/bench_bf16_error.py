"""bf16-vs-f32 error at run length (VERDICT r3 #4).

The bf16 fast path is a *labeled precision trade* the user opts into with
--dtype bf16 (BASELINE.md): it halves per-step memory traffic. Round 3
documented its error at 4 steps only; this harness characterizes the
error-vs-steps curve out to the reference's full 1000-step run
(/root/reference/scripts/diffusion_2D_perf.jl:47 — nt=1000) at the
acceptance geometry (252²), so the trade's cost is known at the run length
the claim covers.

Protocol: advance the SAME per-step masked program (the schedule --dtype
selects, models.diffusion variant 'perf' → ops.pallas_kernels.masked_step)
in f32 and in bf16 from the same Gaussian IC; at log-spaced checkpoints
report the relative L2 error, the max pointwise error against the field
scale, and the peak-temperature drift (the max(T) decay invariant,
hide.jl:115). Measured finding: the error GROWS with run length — once
per-step field changes fall below bf16's 8-bit mantissa resolution, the
storage rounding accumulates as systematic drift (the bf16 peak decays
slower than f32's) rather than averaging out, so the trade is priced per
run length, not per step.

Run:  python scripts/bench_bf16_error.py            # on the chip
      JAX_PLATFORMS=cpu python scripts/bench_bf16_error.py --steps 128
                                                    # interpret-mode CPU
Output: one table row per checkpoint (committed as docs/bf16_error_r4.txt);
tests/test_bf16_error.py pins the 128-step bound from the same machinery.
"""

import argparse
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))


from rocm_mpi_tpu.utils.backend import apply_platform_override  # noqa: E402


def error_curve(n=252, checkpoints=(1, 2, 4, 8, 16, 32, 64, 128, 256, 512,
                                    1000)):
    """[(steps, rel_l2, rel_max, peak_f32, peak_bf16), ...] for the per-step
    masked program at n² — shared by the chip harness and the CPU test."""
    import jax
    import numpy as np

    from rocm_mpi_tpu.config import DiffusionConfig
    from rocm_mpi_tpu.models import HeatDiffusion

    states = {}
    advances = {}
    for dtype in ("f32", "bf16"):
        cfg = DiffusionConfig(
            global_shape=(n, n), lengths=(10.0, 10.0),
            nt=max(checkpoints), warmup=0, dtype=dtype, dims=(1, 1),
        )
        model = HeatDiffusion(cfg)
        T, Cp = model.init_state()
        states[dtype] = (T, Cp)
        advances[dtype] = model.advance_fn("perf")

    rows = []
    done = 0
    for ck in checkpoints:
        delta = ck - done
        for dtype in ("f32", "bf16"):
            T, Cp = states[dtype]
            T = advances[dtype](T, Cp, delta)
            states[dtype] = (T, Cp)
        done = ck
        a = np.asarray(states["f32"][0], dtype=np.float64)
        b = np.asarray(states["bf16"][0], dtype=np.float64)
        scale = np.abs(a).max()
        rel_l2 = float(np.linalg.norm(b - a) / np.linalg.norm(a))
        rel_max = float(np.abs(b - a).max() / scale)
        rows.append((ck, rel_l2, rel_max, float(a.max()), float(b.max())))
    return rows


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--n", type=int, default=252)
    p.add_argument("--steps", type=int, default=1000,
                   help="last checkpoint (smaller for interpret-mode runs)")
    args = p.parse_args(argv)

    apply_platform_override()
    import jax

    plat = jax.devices()[0].platform
    print(f"device: {jax.devices()[0]} ({plat}); {args.n}² per-step masked "
          f"program, f32 vs bf16 from the same Gaussian IC", flush=True)
    if plat == "cpu":
        print("NOTE: interpret-mode Pallas (no accelerator) — error values "
              "are valid, rates are not measured here", flush=True)
    cks = [c for c in (1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1000)
           if c <= args.steps]
    if cks[-1] != args.steps:
        cks.append(args.steps)
    print(f"{'steps':>6}  {'rel L2':>10}  {'rel max':>10}  "
          f"{'max(T) f32':>12}  {'max(T) bf16':>12}")
    for ck, l2, mx, pa, pb in error_curve(args.n, tuple(cks)):
        print(f"{ck:6d}  {l2:10.4%}  {mx:10.4%}  {pa:12.6f}  {pb:12.6f}",
              flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
