"""N-process gloo launch of the weak-scaling harness (the `srun -n N
--mpi=pmix` analog, /root/reference/README.md:18): plays the launcher via
the shared RMT_* contract implementation
(rocm_mpi_tpu.parallel.launcher.spawn_ranks), each rank contributing
`--cpu-devices` virtual devices, so the largest mesh spans every process.
A mechanics record (the interpret-mode rates are meaningless) proving the
scaling loop, the pytree/deep exchanges, and the rank-0-gated reporting
all cross real process boundaries at N > 2 — the committed artifact is
docs/weak_scaling_gloo4_mechanics_r4.jsonl.

    python scripts/run_multiproc_mechanics.py [nprocs] [-- extra flags...]
"""

import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

from rocm_mpi_tpu.parallel.launcher import spawn_ranks  # noqa: E402

ROOT = pathlib.Path(__file__).resolve().parent.parent


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    nprocs = int(argv.pop(0)) if argv and argv[0].isdigit() else 4
    if argv and argv[0] == "--":
        argv.pop(0)
    app_flags = argv or [
        "--cpu-devices", "2", "--local", "16", "--nt", "24",
        "--warmup", "8", "--counts", "2,4,8", "--workload", "swe",
        "--variant", "deep", "--deep-k", "8", "--json",
    ]
    results = spawn_ranks(
        [str(ROOT / "apps" / "weak_scaling.py")] + app_flags,
        nprocs=nprocs,
        timeout=1200,
        init_timeout_s=120,
    )
    rc = 0
    for pid, (p, (out, err)) in enumerate(results):
        if p.returncode != 0:
            rc = 1
            print(f"rank {pid} FAILED rc={p.returncode}\n{err[-2000:]}",
                  file=sys.stderr)
    # Rank 0 owns the report (log0-gated); echo its JSON rows.
    for ln in results[0][1][0].splitlines():
        if ln.startswith("{"):
            print(ln)
    return rc


if __name__ == "__main__":
    sys.exit(main())
