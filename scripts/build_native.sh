#!/usr/bin/env bash
# Build the native components — the analog of the reference's startup.sh
# dependency bootstrap (/root/reference/startup.sh installs pinned Julia
# deps; here the only build artifact is the C++ host-staging engine).
set -euo pipefail
cd "$(dirname "$0")/.."
make -C native
python - <<'EOF'
from rocm_mpi_tpu.parallel import native_halo
assert native_halo.available(), "native library failed its ABI probe"
print("native halostage engine built and loadable")
EOF
