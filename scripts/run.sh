#!/usr/bin/env bash
# App launcher — the analog of the reference's scripts/runme.sh
# (/root/reference/scripts/runme.sh: sources setenv, runs one diffusion app
# under srun). Select the app by argument instead of editing comments
# (README.md:21 documents the reference's comment-toggling).
#
# Usage:
#   scripts/run.sh ap|kp|perf|perf_hide|prof|3d|ring|scale|wave|swe|bounds [extra app flags...]
#   RMT_DISTRIBUTED=1 scripts/run.sh perf_hide      # multi-host pod slice
set -euo pipefail
cd "$(dirname "$0")/.."
source scripts/setenv.sh "${RMT_TRANSPORT_ARG:-}"

app="${1:-ap}"
shift || true
case "$app" in
  ap) exec python apps/diffusion_2d_ap.py "$@" ;;
  kp) exec python apps/diffusion_2d_kp.py "$@" ;;
  perf) exec python apps/diffusion_2d_perf.py "$@" ;;
  perf_hide|hide) exec python apps/diffusion_2d_perf_hide.py "$@" ;;
  prof|perf_hide_prof) exec python apps/diffusion_2d_perf_hide_prof.py "$@" ;;
  3d) exec python apps/diffusion_3d_perf_hide.py "$@" ;;
  ring) exec python apps/ici_ring_test.py "$@" ;;
  scale|weak_scaling) exec python apps/weak_scaling.py "$@" ;;
  wave) exec python apps/wave_2d.py "$@" ;;
  swe) exec python apps/swe_2d.py "$@" ;;
  bounds) exec python scripts/bench_bounds.py "$@" ;;
  *) echo "unknown app '$app' (ap|kp|perf|perf_hide|prof|3d|ring|scale|wave|swe|bounds)" >&2; exit 2 ;;
esac
