"""Stripe-shape sweep for the temporal-blocked HBM kernel (run_hbm_blocked).

The production configuration (_TB_TM=16 stripe rows, _TB_G=8 ghost rows,
k<=8 steps/sweep) re-reads (tm+2g)/tm = 2x the field per sweep and pays the
same redundancy in VPU work. A taller stripe cuts both: tm=32 reads 1.5x
and computes 1.5x. This script times candidate (tm, g, k) on the chip at
the reference's 12288² f32 geometry, within one process (tunnel variance
cancels; baseline measured first and last), after a compiled correctness
check at 768² against the production configuration.

    python scripts/bench_tb_stripes.py [timed_steps]

The winner gets productized as the module constants in ops/pallas_kernels.py
with the measured numbers in BASELINE.md.
"""

import functools
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from rocm_mpi_tpu.ops.pallas_kernels import _make_tb_sweep, edge_masked_cm
from rocm_mpi_tpu.utils import metrics
from rocm_mpi_tpu.utils.backend import enable_persistent_cache, require_accelerator

N = 12288
CHECK_N = 768
LAM, CP0 = 1.0, 1.0

# (tm, g, k): stripe rows, ghost rows (= max k), steps per sweep.
CASES = [
    (16, 8, 8),   # production baseline
    (24, 8, 8),   # 1.67x redundancy
    (32, 8, 8),   # 1.5x redundancy
    (48, 8, 8),   # 1.33x — likely past the Mosaic/VMEM boundary
    (32, 16, 16),  # deeper sweeps: 2x redundancy but half the sweeps
]


def make_advance(T0, tm, g, k, inv_d2):
    sweep = _make_tb_sweep(T0, inv_d2, k, g, tm, interpret=False)

    @functools.partial(jax.jit, donate_argnums=0)
    def advance(T, Cm, n_sweeps):
        return lax.fori_loop(0, n_sweeps, lambda _, x: sweep(x, Cm), T)

    return advance


def state(n, key=0):
    spacing = 10.0 / n
    inv = 1.0 / (spacing * spacing)
    T0 = jax.random.uniform(jax.random.PRNGKey(key), (n, n), jnp.float32)
    Cp = jnp.full((n, n), CP0, jnp.float32)
    dt = spacing * spacing * CP0 / LAM / 4.1
    return T0, edge_masked_cm(T0, Cp, LAM, dt), (inv, inv)


def main():
    enable_persistent_cache()
    timed = int(sys.argv[1]) if len(sys.argv) > 1 else 1600
    require_accelerator("bench_tb_stripes.py")
    dev = jax.devices()[0]
    print(f"device: {dev} | {N}² f32 | timed {timed} steps")

    # Correctness referee at CHECK_N: production config, 32 steps.
    Tc, Cmc, invc = state(CHECK_N)
    ref = np.asarray(make_advance(Tc, 16, 8, 8, invc)(
        jnp.copy(Tc), Cmc, 32 // 8))

    T0, Cm, inv_d2 = state(N)
    order = CASES + [CASES[0]]
    advances = {}  # one compile + one referee check per case; repeats reuse
    for i, (tm, g, k) in enumerate(order):
        label = f"tm={tm} g={g} k={k}"
        try:
            if (tm, g, k) not in advances:
                chk = make_advance(Tc, tm, g, k, invc)
                out = np.asarray(chk(jnp.copy(Tc), Cmc, 32 // k))
                np.testing.assert_allclose(out, ref, rtol=2e-6, atol=1e-7)
                advances[(tm, g, k)] = make_advance(T0, tm, g, k, inv_d2)
            adv = advances[(tm, g, k)]
            nsw = timed // k
            T = adv(jnp.copy(T0), Cm, max(1, 16 // k))  # warmup/compile
            timer = metrics.Timer()
            timer.tic(T)
            T = adv(T, Cm, nsw)
            w = timer.toc(T)
            us = w / (nsw * k) * 1e6
            gpts = N * N / (w / (nsw * k)) / 1e9
            eq_gbs = 3 * N * N * 4 / (w / (nsw * k)) / 1e9
            print(f"[{i}] {label:18s} {us:9.3f} us/step  {gpts:7.2f} Gpts/s  "
                  f"T_eff(equiv)={eq_gbs:7.1f} GB/s")
        except Exception as e:  # compile/VMEM failures are data, not crashes
            lines = [ln for ln in str(e).splitlines() if ln.strip()]
            msg = lines[0][:120] if lines else type(e).__name__
            print(f"[{i}] {label:18s} FAILED: {msg}")


if __name__ == "__main__":
    main()
