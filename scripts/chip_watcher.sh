#!/usr/bin/env bash
# Tunnel-recovery watcher for the round-5 chip work.
#
# The chip tunnel flaps (documented multi-hour outages in BASELINE.md); a
# measurement session must start the moment a healthy window opens. This
# loop probes with a bounded-timeout trivial jit every ~60 s; on each
# healthy probe it runs, in priority order (VERDICT r4 next #1 — the
# round-4 ordering spent the only healthy window on compiles and banked
# nothing):
#   1. bench.py — the driver-contract headline number. Emit-as-you-go
#      lands a real chip rate on stdout in seconds (floor kernel compiles
#      fast; the persistent cache makes retries instant), so even a
#      minutes-long window banks the one number the round is scored on.
#   2. scripts/run_chip_queue.sh — the measurement debt, value-ordered,
#      per-artifact resumable.
#   3. the compiled-Mosaic tier, one ranked sub-group at a time
#      (pytest -m g1..g4, tests_tpu/conftest.py), each group's log
#      promoted independently — a short window still banks g1 (the
#      scored-path kernels) instead of an all-or-INCOMPLETE log.
#
# Usage: nohup scripts/chip_watcher.sh > .watcher_r5.log 2>&1 &
# (log path deliberately untracked — the live file grows while the watcher
# runs; commit a snapshot into docs/ only after it finishes)
set -u
cd "$(dirname "$0")/.."

# Pre-flight: the graftlint gate (scripts/lint.sh, <5 s, no device). A
# donation/purity/compat finding means the measurement code is carrying a
# known-corrupting bug class — bank nothing until it's fixed: a whole
# healthy window spent measuring a racy program is worse than a late start.
if ! bash scripts/lint.sh; then
  echo "[watcher] graftlint gate FAILED — fix findings before measuring" >&2
  exit 2
fi

# Children honor this dir via utils.backend.enable_persistent_cache() /
# tests_tpu/conftest.py (which also set the persist-everything thresholds
# themselves — no point exporting those here, they'd be overridden).
export JAX_COMPILATION_CACHE_DIR="${JAX_COMPILATION_CACHE_DIR:-$PWD/.jax_cache}"

DEADLINE=$(( $(date +%s) + ${WATCH_HOURS:-11} * 3600 ))
# The ranked sub-groups come FROM tests_tpu/conftest.py (its _GROUPS line)
# so the two lists cannot drift; the fallback only covers a parse failure.
TIER_GROUPS=($(sed -n 's/^_GROUPS = (\(.*\))$/\1/p' tests_tpu/conftest.py | tr -d '",'))
[ "${#TIER_GROUPS[@]}" -gt 0 ] || TIER_GROUPS=(g1 g2 g3 g4)

probe() {
  # A CPU fallback must NOT count as healthy: when the accelerator plugin
  # fails init, jax can fall back to CPU and the trivial jit would pass —
  # firing the one-shot queue into CPU garbage and losing the real window.
  timeout -k 10 180 python - <<'EOF'
import jax, jax.numpy as jnp
dev = jax.devices()[0]
if dev.platform == "cpu":
    raise SystemExit(f"probe: CPU fallback ({dev}), tunnel not healthy")
x = jnp.ones((128, 128), jnp.float32)
r = jax.jit(lambda a: a * 2 + 1)(x)
r.block_until_ready()
print("probe ok on", dev)
EOF
}

headline_done() {
  # Complete = the promoted log's last JSON line is a real accelerator
  # measurement (no "error" field — smoke/fallback lines carry one).
  [ -s docs/bench_headline_r5.txt ] \
    && grep -q '"metric"' docs/bench_headline_r5.txt \
    && ! tail -1 docs/bench_headline_r5.txt | grep -q '"error"'
}

run_headline() {
  # The headline budget is deliberately modest: with the floor emit a real
  # number lands in well under a minute; 300 s covers cold-cache compiles.
  # The outer kill is derived from the budget (+60 s grace) so raising
  # BENCH_HEADLINE_BUDGET_S can never make the wrapper kill bench.py
  # before its own parent prints the contract line.
  local budget="${BENCH_HEADLINE_BUDGET_S:-300}"
  budget="${budget%%.*}"  # bench.py accepts floats; bash arithmetic doesn't
  [[ "$budget" =~ ^[0-9]+$ ]] || budget=300
  BENCH_BUDGET_S="$budget" \
    timeout -k 15 $((budget + 60)) python bench.py > docs/bench_headline_r5.txt.part 2> .bench_headline_stderr.log
  local rc=$?
  cat .bench_headline_stderr.log
  cat docs/bench_headline_r5.txt.part
  if [ "$rc" -eq 0 ] && [ -s docs/bench_headline_r5.txt.part ] \
      && ! tail -1 docs/bench_headline_r5.txt.part | grep -q '"error"'; then
    { echo "# bench.py headline run at $(date -u +%FT%TZ) (stdout contract line last)"
      grep -E "floor|flagship|long window|µs/step|us/step" .bench_headline_stderr.log || true
      cat docs/bench_headline_r5.txt.part; } > docs/bench_headline_r5.txt
    rm -f docs/bench_headline_r5.txt.part
    echo "[watcher] headline banked: $(tail -1 docs/bench_headline_r5.txt)"
  else
    rm -f docs/bench_headline_r5.txt.part
    echo "[watcher] headline attempt rc=$rc did not produce a real chip line"
  fi
}

archive_telemetry() {
  # Any measurement child that ran with telemetry (--telemetry DIR or
  # RMT_TELEMETRY_DIR, docs/TELEMETRY.md) left per-rank JSONL streams;
  # bank them next to the watcher's other logs so a mid-watch flap can't
  # lose the only per-phase attribution of a healthy window. cp -p keeps
  # re-archiving idempotent (append-only files, newest copy wins).
  local tdir="${RMT_TELEMETRY_DIR:-$PWD/output/telemetry}"
  local found=0 f
  if [ -d "$tdir" ]; then
    for f in "$tdir"/telemetry-rank*.jsonl "$tdir"/telemetry-summary.json \
             "$tdir"/telemetry-trace.json "$tdir"/heartbeat-rank*.json \
             "$tdir"/postmortem-rank*.json "$tdir"/postmortem-rank*.traceback \
             "$tdir"/elastic.jsonl "$tdir"/manifest-*.json; do
      [ -s "$f" ] || continue
      mkdir -p docs/telemetry_r5
      cp -p "$f" docs/telemetry_r5/ && found=$((found + 1))
    done
    # elastic.jsonl + manifest-*.json above: an elastic drill's shrink/
    # grow records and the v2 topology-metadata manifests
    # (docs/RESILIENCE.md "Elastic recovery" and §7) — the artifacts
    # that explain WHY a window finished on a different mesh than it
    # started with (and whether a preemption or storage outage drove
    # the change).
    # A watchdog verdict leaves a postmortem/ bundle (docs/TELEMETRY.md
    # "Health plane"): the one artifact that explains a wedged window
    # after the tunnel flaps — archive it whole, next to the telemetry.
    if [ -d "$tdir/postmortem" ]; then
      mkdir -p docs/telemetry_r5/postmortem
      cp -pr "$tdir/postmortem/." docs/telemetry_r5/postmortem/ \
        && found=$((found + 1))
    fi
  fi
  # Grow/preempt/storage drill sidecars (docs/RESILIENCE.md §7): the
  # elastic supervisor writes each drill's elastic.jsonl next to that
  # drill's OWN checkpoint/health dir under output/, not the default
  # telemetry sink — archive them under per-drill names so the shrink→
  # grow and preempted-eviction decision trails survive a flap, and so
  # lint.sh's schema glob (docs/telemetry_r*/elastic*.jsonl) gates them.
  # Soak + serving sidecars (docs/SERVING.md; docs/RESILIENCE.md §8):
  # the bounded soak's schema-versioned report (SLO block, episode
  # verdicts), its append-only quarantine ledger, and the per-episode
  # bin manifests — the burst's all-planes-compose evidence. Archived
  # under docs/telemetry_r5/ where lint.sh's soak-report*/quarantine*/
  # serve-manifest* schema globs gate them.
  local s
  # ... plus the fleet sidecars (docs/SERVING.md "The fleet"): the
  # router's ticket journal and merged report from the soak's fleet
  # episode — lint.sh's fleet-journal*/fleet-report* globs gate the
  # copies. run_fleet_smoke's standalone pair is archived under
  # distinct -smoke names below (same base names, different run).
  for s in output/soak/soak-report.json \
           output/soak/quarantine.jsonl \
           output/soak/serve-manifest-*.json \
           output/soak/gloo-serve/serve-manifest.json \
           output/soak/gloo-serve/serve-requests.jsonl \
           output/soak/fleet-journal.jsonl \
           output/soak/fleet-report.json; do
    [ -s "$s" ] || continue
    mkdir -p docs/telemetry_r5
    cp -p "$s" docs/telemetry_r5/ && found=$((found + 1))
  done
  if [ -s output/fleet/fleet-journal.jsonl ]; then
    mkdir -p docs/telemetry_r5
    cp -p output/fleet/fleet-journal.jsonl \
      docs/telemetry_r5/fleet-journal-smoke.jsonl && found=$((found + 1))
  fi
  if [ -s output/fleet/fleet-report.json ]; then
    mkdir -p docs/telemetry_r5
    cp -p output/fleet/fleet-report.json \
      docs/telemetry_r5/fleet-report-smoke.json && found=$((found + 1))
  fi
  local e ename
  for e in output/*/elastic.jsonl; do
    [ -s "$e" ] || continue
    [ "$e" -ef "$tdir/elastic.jsonl" ] && continue  # archived above
    ename="elastic-$(basename "$(dirname "$e")").jsonl"
    mkdir -p docs/telemetry_r5
    cp -p "$e" "docs/telemetry_r5/$ename" && found=$((found + 1))
  done
  # The bench trajectory (BENCH_r{n}.json, written by bench.py --suite in
  # the telemetry regress flat-metrics format) is banked alongside: a
  # mid-watch flap must not lose the only completed-suite record either.
  for f in BENCH_r*.json; do
    [ -s "$f" ] || continue
    mkdir -p docs/telemetry_r5
    cp -p "$f" docs/telemetry_r5/ && found=$((found + 1))
  done
  # The autotuner cache (output/tuning/cache.json, written by
  # run_tuning_search below): the chip-fingerprinted winners are the
  # round's most reusable artifact — the next session's bench/suite runs
  # start from a tuned config instead of a guessed one, but only if the
  # cache survives the flap. Archived under a distinct name so lint.sh's
  # schema glob finds it (docs/telemetry_r*/tuning-cache*.json).
  if [ -s output/tuning/cache.json ]; then
    mkdir -p docs/telemetry_r5
    cp -p output/tuning/cache.json docs/telemetry_r5/tuning-cache.json \
      && found=$((found + 1))
  fi
  # The graftlint findings artifact (output/lint/findings.json, written
  # by the pre-flight lint.sh): the machine-readable record of WHICH
  # analyzer verdict this burst was measured under — a later "the
  # numbers look off" triage can check whether the tree was clean, what
  # was baselined, and what was suppressed. Archived under a distinct
  # name so lint.sh's schema glob finds it
  # (docs/telemetry_r*/lint-findings*.json).
  if [ -s output/lint/findings.json ]; then
    mkdir -p docs/telemetry_r5
    cp -p output/lint/findings.json docs/telemetry_r5/lint-findings.json \
      && found=$((found + 1))
  fi
  [ "$found" -gt 0 ] && echo "[watcher] archived $found telemetry/bench file(s) into docs/telemetry_r5/"
  return 0
}

run_tuning_search() {
  # Autotuner search at the benchmark geometry (docs/PERF.md
  # "Autotuning"): winners are fingerprinted to THIS chip's jax/backend,
  # so the burst is the only place they can be measured honestly. Warm
  # caches are pure hits (search skips measured keys), so re-running
  # every healthy window is cheap; a flap mid-search loses at most one
  # key (atomic per-entry writes). Bounded so a wedged backend cannot
  # eat the window the queue and tier groups still need.
  echo "[watcher] tuning search (252² flagship geometry)"
  timeout -k 15 900 python -m rocm_mpi_tpu.tuning search \
    --shape 252x252 --cache output/tuning/cache.json \
    || echo "[watcher] tuning search rc=$? (continuing; cache keeps prior winners)"
}

suite_done() {
  # The bench trajectory exists once bench.py --suite has banked at
  # least one BENCH_r{n}.json at the repo root (ROADMAP item 5a — the
  # file set was empty for nine perf PRs; the first healthy window must
  # close that gap).
  ls BENCH_r*.json >/dev/null 2>&1
}

run_bench_suite() {
  # bench.py --suite: the full ladder (per-step/VMEM/deep/3D rows, the
  # wire-mode pair, the batched-throughput rung, and the serial-vs-
  # pipelined serving drain rung) banked atomically as BENCH_r{n}.json
  # — the telemetry-regress flat-metrics trajectory record
  # archive_telemetry copies and lint.sh schema-gates. A partial
  # (killed) suite banks nothing by design, so re-running on the next
  # healthy probe is safe. Bounded so a wedged backend cannot eat the
  # rest of the window.
  if suite_done; then
    echo "[watcher] bench suite already banked — skipping"
    return 0
  fi
  echo "[watcher] bench.py --suite (the BENCH_r{n}.json trajectory)"
  timeout -k 15 3600 python bench.py --suite \
    || echo "[watcher] bench suite rc=$? (continuing; no partial record banked)"
}

run_soak() {
  # The bounded chaos soak (docs/RESILIENCE.md §8, ROADMAP item 5) —
  # the ad-hoc serve smoke, grown up: one episode per fault family
  # (queue-flood admission storms, NaN-lane quarantine, circuit-breaker
  # open→half-open→recover, session-save storage outages, a real
  # SIGTERM eviction, and the 2-rank gloo serve + kill drills) under a
  # deterministic rolling schedule, with SLO accounting (latency
  # p50/p99 from real telemetry, deadline-miss rate, rejected/expired/
  # quarantined totals) banked atomically in soak-report.json plus the
  # append-only quarantine.jsonl poison ledger (archive_telemetry
  # copies both; lint.sh schema-checks the archived copies). Bounded +
  # timeout so a wedged backend cannot eat the window.
  echo "[watcher] bounded chaos soak (all fault planes composed)"
  timeout -k 15 900 python apps/soak.py --bounded --out output/soak \
    || echo "[watcher] soak rc=$? (continuing; report still archived)"
}

run_fleet_smoke() {
  # The multi-replica fleet smoke (docs/SERVING.md "The fleet"): a
  # bounded 2-replica apps/fleet.py run — router affinity, the durable
  # ticket journal, and the merged report exercised on the real
  # backend each healthy burst. Banks fleet-journal.jsonl +
  # fleet-report.json under output/fleet (archive_telemetry copies
  # them; lint.sh schema-gates the archived copies). Bounded so a
  # wedged backend cannot eat the window.
  echo "[watcher] fleet smoke (2 replicas, 12 synthetic requests)"
  timeout -k 15 600 python apps/fleet.py --replicas 2 --synthetic 12 \
    --out output/fleet \
    || echo "[watcher] fleet rc=$? (continuing...)"
}

group_log() { echo "docs/tpu_tier_${1}_r5.txt"; }

group_done() {
  # Promoted only on pytest rc=0 with a pass count and no NO-ACCELERATOR
  # skip (a mid-window CPU fallback would green-skip the whole group; the
  # -rs run prints each skip's reason, so the backend-guard reason from
  # tests_tpu/conftest.py is grep-able). A conditional skip added for any
  # OTHER reason must not make the group permanently unpromotable
  # (ADVICE r5 #2).
  local log; log="$(group_log "$1")"
  [ -s "$log" ] \
    && grep -qE "[0-9]+ passed" "$log" \
    && ! grep -q "needs a TPU backend" "$log" \
    && ! grep -q "^INCOMPLETE" "$log"
}

tier_done() {
  local g
  for g in "${TIER_GROUPS[@]}"; do
    group_done "$g" || return 1
  done
  return 0
}

run_tier_groups() {
  local g log rc
  for g in "${TIER_GROUPS[@]}"; do
    if group_done "$g"; then
      echo "[watcher] tier $g already green — skipping"
      continue
    fi
    log="$(group_log "$g")"
    echo "[watcher] tier $g starting at $(date -u +%H:%M:%S)"
    # -rs: print skip reasons, so promotion can tell the fatal
    # no-accelerator skip from a benign conditional one (group_done).
    timeout -k 15 2400 python -m pytest tests_tpu/ -m "$g" -q -rs 2>&1 | tee "${log}.part"
    rc=${PIPESTATUS[0]}
    if [ "$rc" -eq 0 ] && grep -qE "[0-9]+ passed" "${log}.part" \
        && ! grep -q "needs a TPU backend" "${log}.part"; then
      mv "${log}.part" "$log"
    else
      { echo "INCOMPLETE rc=$rc at $(date -u +%FT%TZ)"
        cat "${log}.part"; } > "$log"
      rm -f "${log}.part"
      echo "[watcher] tier $g rc=$rc — re-probing before the next group"
      # A failed group usually means the tunnel dropped mid-compile: fall
      # out to the main loop rather than burn the remaining groups' time.
      return 1
    fi
  done
  return 0
}

n=0
headline_fails=0
while [ "$(date +%s)" -lt "$DEADLINE" ]; do
  n=$((n + 1))
  echo "[watcher] probe $n at $(date -u +%H:%M:%S)"
  if probe; then
    if headline_done; then
      echo "[watcher] headline already banked — skipping"
    else
      echo "[watcher] tunnel healthy — headline bench first"
      run_headline
      if headline_done; then
        headline_fails=0
      else
        headline_fails=$((headline_fails + 1))
        # A failed headline right after a green probe is usually a
        # mid-window flap: don't hand the queue hours of hard timeouts
        # against a stalled backend — re-probe first (same fail-fast
        # policy run_tier_groups applies between groups). But a
        # DETERMINISTIC bench failure (healthy tunnel, reproducible
        # crash) must not starve priorities 2 and 3 for the whole
        # watch: after 2 consecutive failures, fall through anyway.
        if [ "$headline_fails" -lt 2 ]; then
          echo "[watcher] headline failed post-probe — re-probing before queue"
          sleep 60
          continue
        fi
        echo "[watcher] headline failed ${headline_fails}x — falling through to queue/tier"
      fi
    fi
    echo "[watcher] running measurement queue"
    bash scripts/run_chip_queue.sh
    queue_rc=$?
    run_tuning_search
    run_bench_suite
    run_soak
    run_fleet_smoke
    run_tier_groups
    archive_telemetry
    if headline_done && [ "$queue_rc" -eq 0 ] && tier_done; then
      # Don't stop at the first healthy window otherwise: a mid-queue flap
      # leaves INCOMPLETE artifacts, and the skip-complete logic makes
      # later passes cheap — keep watching until everything is done.
      echo "[watcher] all artifacts complete at $(date -u +%H:%M:%S)"
      exit 0
    fi
    echo "[watcher] incomplete artifacts remain; continuing to watch"
  else
    echo "[watcher] tunnel down"
  fi
  sleep 60
done
echo "[watcher] deadline reached with work remaining"
exit 1
