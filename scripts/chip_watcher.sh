#!/usr/bin/env bash
# Tunnel-recovery watcher for the round-4 chip queue.
#
# The chip tunnel flaps (documented multi-hour outages in BASELINE.md); a
# measurement session must start the moment a healthy window opens. This
# loop probes with a bounded-timeout trivial jit every ~60 s; on the first
# healthy probe it runs, in priority order:
#   1. the compiled-Mosaic test tier (tests_tpu/, live-tee'd log)
#   2. scripts/run_chip_queue.sh (the BASELINE.md measurement debt)
# The persistent XLA compilation cache is enabled for every child, so a
# mid-queue drop never re-pays compiles already done.
#
# Usage: nohup scripts/chip_watcher.sh > .watcher_r4.log 2>&1 &
# (log path deliberately untracked — the live file grows while the watcher
# runs; commit a snapshot into docs/ only after it finishes)
set -u
cd "$(dirname "$0")/.."

# Children honor this dir via utils.backend.enable_persistent_cache() /
# tests_tpu/conftest.py (which also set the persist-everything thresholds
# themselves — no point exporting those here, they'd be overridden).
export JAX_COMPILATION_CACHE_DIR="${JAX_COMPILATION_CACHE_DIR:-$PWD/.jax_cache}"

DEADLINE=$(( $(date +%s) + ${WATCH_HOURS:-10} * 3600 ))

probe() {
  # A CPU fallback must NOT count as healthy: when the accelerator plugin
  # fails init, jax can fall back to CPU and the trivial jit would pass —
  # firing the one-shot queue into CPU garbage and losing the real window.
  timeout -k 10 180 python - <<'EOF'
import jax, jax.numpy as jnp
dev = jax.devices()[0]
if dev.platform == "cpu":
    raise SystemExit(f"probe: CPU fallback ({dev}), tunnel not healthy")
x = jnp.ones((128, 128), jnp.float32)
r = jax.jit(lambda a: a * 2 + 1)(x)
r.block_until_ready()
print("probe ok on", dev)
EOF
}

tier_done() {
  # The log is only promoted to this path on pytest rc=0 (else it gets an
  # INCOMPLETE header), so done = exists, has a pass count, no header.
  [ -s docs/tpu_test_log_r4.txt ] \
    && grep -qE "[0-9]+ passed" docs/tpu_test_log_r4.txt \
    && ! grep -q "^INCOMPLETE" docs/tpu_test_log_r4.txt
}

n=0
while [ "$(date +%s)" -lt "$DEADLINE" ]; do
  n=$((n + 1))
  echo "[watcher] probe $n at $(date -u +%H:%M:%S)"
  if probe; then
    if tier_done; then
      echo "[watcher] compiled tier already passed — skipping"
    else
      echo "[watcher] tunnel healthy — running compiled tier"
      timeout -k 15 3000 python -m pytest tests_tpu/ -q 2>&1 | tee docs/tpu_test_log_r4.txt.part
      rc=${PIPESTATUS[0]}
      if [ "$rc" -eq 0 ]; then
        mv docs/tpu_test_log_r4.txt.part docs/tpu_test_log_r4.txt
      else
        { echo "INCOMPLETE rc=$rc at $(date -u +%FT%TZ)"
          cat docs/tpu_test_log_r4.txt.part; } > docs/tpu_test_log_r4.txt
        rm -f docs/tpu_test_log_r4.txt.part
      fi
      echo "[watcher] compiled tier rc=$rc — running measurement queue"
    fi
    if bash scripts/run_chip_queue.sh && tier_done; then
      # Don't stop at the first healthy window: a mid-queue flap leaves
      # INCOMPLETE artifacts, and run()'s skip-complete logic makes later
      # passes cheap — keep watching until everything is actually done.
      echo "[watcher] all artifacts complete at $(date -u +%H:%M:%S)"
      exit 0
    fi
    echo "[watcher] incomplete artifacts remain; continuing to watch"
  else
    echo "[watcher] tunnel down"
  fi
  sleep 60
done
echo "[watcher] deadline reached with work remaining"
exit 1
