#!/usr/bin/env bash
# Round-5 chip measurement queue (BASELINE.md "pending" debt).
# Runs every chip-gated harness in VALUE order, tee-ing each artifact into
# docs/. Serialized on purpose: one process owns the TPU. Each entry gets a
# hard timeout so one wedged run can't starve the rest; artifacts are
# written incrementally so a mid-queue tunnel drop keeps what finished.
#
# Value order (VERDICT r4 next #2/#3): `bounds` first — its pure-DMA shape
# sweep is the one artifact that closes the per-step 12288² parity
# argument; then the A/Bs that can move shipped defaults (kernel forms,
# pending two rounds; strip overhead; tb stripes); then the bf16 chip
# error curves; the full suite refresh runs LAST because it is the longest
# entry and should measure whatever defaults the A/Bs justify.
set -u
cd "$(dirname "$0")/.."

QUEUE_ARTIFACTS=()

run() { # name timeout_s cmd...
  local name="$1" t="$2"; shift 2
  local out="docs/${name}_r5.txt"
  QUEUE_ARTIFACTS+=("$out")
  if [ -s "$out" ] && ! grep -q "^INCOMPLETE" "$out"; then
    echo "== $name: artifact $out already complete, skipping =="
    return 0
  fi
  echo "== $name (timeout ${t}s) =="
  # tee to a temp file and promote only on rc=0, so a re-run that dies
  # mid-entry can never destroy a previously completed artifact.
  timeout -k 10 "$t" "$@" 2>&1 | tee "${out}.part"
  local rc=${PIPESTATUS[0]}
  if [ "$rc" -eq 0 ]; then
    mv "${out}.part" "$out"
  else
    { echo "INCOMPLETE rc=$rc at $(date -u +%FT%TZ)"; cat "${out}.part"; } > "$out"
    rm -f "${out}.part"
  fi
  echo "-- $name rc=$rc"
}

run perstep_bounds  1800 python scripts/bench_bounds.py
run kernel_forms    1800 python scripts/bench_kernel_forms.py
run strip_overhead  1800 python scripts/bench_strip_overhead.py --require-accelerator
run tb_stripes      2400 python scripts/bench_tb_stripes.py
run bf16_error_chip 1800 python scripts/bench_bf16_error.py --require-accelerator
run bf16_error_vmem_chip 1800 python scripts/bench_bf16_error.py --schedule vmem --require-accelerator
run bench_suite     3600 python bench.py --suite --require-accelerator
# Completeness is judged ONLY over the artifacts this queue owns — other
# docs/*_r5.txt files (the watcher's tier logs, the headline bench record)
# are not this script's to report on.
incomplete=0
for out in "${QUEUE_ARTIFACTS[@]}"; do
  if [ ! -s "$out" ] || grep -q "^INCOMPLETE" "$out"; then
    incomplete=$((incomplete + 1))
  fi
done
echo "== queue done (INCOMPLETE artifacts: $incomplete) =="
exit "$incomplete"
